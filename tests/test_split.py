"""PG splitting tier: pg_num growth under data, placement invariants,
live writes through the split, autoscaler apply.

Reference parity: PG::split_into (/root/reference/src/osd/PG.cc:578),
OSDMonitor's pg_num ratchet, and the pg_autoscaler's `on` mode.
"""

import asyncio

import numpy as np
import pytest

from ceph_tpu.osd.osdmap import PgId, _calc_mask, ceph_stable_mod
from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

from cluster_helpers import Cluster


def test_stable_mod_split_children():
    """Objects move only to children of their parent (ps + k*old_num)
    — the invariant that makes local splitting complete."""
    rng = np.random.default_rng(0)
    for old, new in ((8, 16), (8, 32), (4, 12)):
        mask_old = _calc_mask(old)
        mask_new = _calc_mask(new)
        for i in range(500):
            h = ceph_str_hash_rjenkins(f"obj-{i}".encode())
            ps_old = ceph_stable_mod(h, old, mask_old)
            ps_new = ceph_stable_mod(h, new, mask_new)
            assert ps_new % old == ps_old % old or ps_new == ps_old, \
                (old, new, ps_old, ps_new)
            if ps_new != ps_old:
                assert ps_new >= old  # always a NEW pg, never another
                # pre-existing one


def _payloads(n, seed=7):
    return {f"obj-{i}": np.random.default_rng(seed + i).integers(
        0, 256, 2000 + 997 * i % 30000, dtype=np.uint8).tobytes()
        for i in range(n)}


@pytest.mark.parametrize("pool_kind", ["replicated", "ec"])
def test_split_preserves_data(pool_kind):
    """Grow pg_num 4->16 with data at rest: every object must read
    back through its NEW placement, and the new PGs must go active."""

    async def run():
        cluster = Cluster(num_osds=6, osds_per_host=2)
        await cluster.start()
        try:
            if pool_kind == "ec":
                await cluster.client.create_ec_pool(
                    "sp", {"plugin": "ec_jax",
                           "technique": "reed_sol_van", "k": "2",
                           "m": "1", "crush-failure-domain": "osd",
                           "tpu": "false"}, pg_num=4)
            else:
                await cluster.client.create_replicated_pool(
                    "sp", size=3, pg_num=4)
            ioctx = cluster.client.open_ioctx("sp")
            payloads = _payloads(24)
            for oid, data in payloads.items():
                await ioctx.write_full(oid, data)
            moved = sum(
                1 for oid in payloads
                if ceph_stable_mod(ceph_str_hash_rjenkins(oid.encode()),
                                   16, _calc_mask(16)) >= 4)
            assert moved > 0  # the test actually exercises movement

            rc, out = await cluster.client.mon_command(
                {"prefix": "osd pool set", "name": "sp",
                 "var": "pg_num", "val": 16})
            assert rc == 0, out
            await cluster.client.wait_for_new_map()
            await cluster.wait_for_clean(timeout=60.0)

            for oid, data in payloads.items():
                got = await ioctx.read(oid)
                assert got == data, f"{oid} lost through split"
            # deletes route to the new placement too
            await ioctx.remove("obj-0")
            from ceph_tpu.rados.client import ObjectNotFound

            try:
                await ioctx.read("obj-0")
                assert False, "removed object still readable"
            except ObjectNotFound:
                pass
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 180))


@pytest.mark.slow
def test_split_under_live_writes():
    """Autoscaler-shaped flow: pg_num grows while a write workload
    runs; model-checked reads after settling."""

    async def run():
        cluster = Cluster(num_osds=6, osds_per_host=2)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "lw", {"plugin": "ec_jax", "technique": "reed_sol_van",
                       "k": "2", "m": "1",
                       "crush-failure-domain": "osd", "tpu": "false"},
                pg_num=8)
            ioctx = cluster.client.open_ioctx("lw")
            model = {}
            maybe: dict = {}
            stop = False

            async def workload():
                seq = 0
                while not stop:
                    seq += 1
                    oid = f"obj-{seq % 20}"
                    data = bytes([seq % 256]) * (1500 + seq % 9000)
                    maybe.setdefault(oid, []).append(data)
                    try:
                        await ioctx.write_full(oid, data)
                        model[oid] = data
                        maybe[oid] = []
                    except Exception:
                        pass
                    await asyncio.sleep(0)

            task = asyncio.get_running_loop().create_task(workload())
            try:
                await asyncio.sleep(1.5)
                rc, out = await cluster.client.mon_command(
                    {"prefix": "osd pool set", "name": "lw",
                     "var": "pg_num", "val": 32})
                assert rc == 0, out
                await asyncio.sleep(3.0)  # write THROUGH the split
            finally:
                stop = True
                await task
            assert len(model) >= 10
            await cluster.wait_for_clean(timeout=90.0)
            for oid, data in model.items():
                got = await ioctx.read(oid)
                legal = [data] + maybe.get(oid, [])
                assert any(got == want for want in legal), \
                    f"{oid} diverged through live split"
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 240))


def test_autoscaler_applies_growth():
    """pg_autoscale_mode=on: the mgr grows an under-provisioned pool
    and the cluster converges."""

    async def run():
        from ceph_tpu.mgr import MgrDaemon

        cluster = Cluster(num_osds=6, osds_per_host=2)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "auto", size=2, pg_num=4)
            ioctx = cluster.client.open_ioctx("auto")
            payloads = _payloads(10)
            for oid, data in payloads.items():
                await ioctx.write_full(oid, data)
            mgr = MgrDaemon(cluster.mon_addrs,
                            config={"pg_autoscale_mode": "on",
                                    "mon_target_pg_per_osd": 32})
            await mgr.start()
            try:
                scaler = mgr.modules["pg_autoscaler"]
                await scaler.serve_once()
                assert scaler.applied.get("auto", 0) > 4, \
                    scaler.recommendations
                await cluster.client.wait_for_new_map()
                await cluster.wait_for_clean(timeout=60.0)
                pool_id = cluster.mon.osdmap.lookup_pool("auto")
                assert cluster.mon.osdmap.pools[pool_id].pg_num > 4
                for oid, data in payloads.items():
                    assert await ioctx.read(oid) == data
            finally:
                await mgr.stop()
        finally:
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 180))

"""Object-class (cls) tests: exec plumbing + in-tree classes.

Mirrors /root/reference/src/test/cls_hello/test_cls_hello.cc,
src/test/cls_lock/test_cls_lock.cc, src/test/cls_numops/ shapes over
the wire against a live mini-cluster, plus the atomicity and
replication properties that make server-side classes worth having.
"""

import asyncio
import json

import pytest

from cluster_helpers import Cluster

from ceph_tpu.rados.client import RadosError


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def _cluster():
    # one OSD per host: a size-3 pool with the default host failure
    # domain needs 3 distinct hosts even after one failure
    cluster = Cluster(num_osds=4, osds_per_host=1)
    await cluster.start()
    await cluster.client.create_replicated_pool("p", size=3, pg_num=8)
    return cluster, cluster.client.open_ioctx("p")


def test_hello_round_trip():
    async def main():
        cluster, io = await _cluster()
        try:
            out = await io.execute("obj", "hello", "say_hello", b"tpu")
            assert out == b"Hello, tpu!"
            # WR method persists state through the normal write path
            await io.execute("obj", "hello", "record_hello", b"ceph")
            assert await io.execute("obj", "hello", "replay") == \
                b"Hello, ceph!"
            assert await io.read("obj") == b"Hello, ceph!"
            # double-record refuses (EEXIST from inside the class)
            with pytest.raises(RadosError):
                await io.execute("obj", "hello", "record_hello", b"x")
            # unknown class/method is EINVAL, not a crash
            with pytest.raises(RadosError):
                await io.execute("obj", "nosuch", "m")
        finally:
            await cluster.stop()

    run(main())


def test_numops_atomic_increments():
    """Concurrent add calls on one key must all land (the class runs
    atomically server-side — the reason numops exists)."""
    async def main():
        cluster, io = await _cluster()
        try:
            req = json.dumps({"key": "ctr", "value": 1}).encode()
            await asyncio.gather(*(
                io.execute("counter", "numops", "add", req)
                for _ in range(20)))
            omap = await io.omap_get("counter")
            assert float(omap["ctr"].decode()) == 20.0
            out = await io.execute(
                "counter", "numops", "mul",
                json.dumps({"key": "ctr", "value": 3}).encode())
            assert float(out.decode()) == 60.0
            with pytest.raises(RadosError):
                await io.execute(
                    "counter", "numops", "div",
                    json.dumps({"key": "ctr", "value": 0}).encode())
        finally:
            await cluster.stop()

    run(main())


def test_lock_exclusive_shared():
    async def main():
        cluster, io = await _cluster()
        try:
            def req(**kw):
                return json.dumps(kw).encode()

            await io.execute("img", "lock", "lock",
                             req(name="l", type="exclusive",
                                 owner="client.a", cookie="c1"))
            # renewal by the same owner+cookie is fine
            await io.execute("img", "lock", "lock",
                             req(name="l", type="exclusive",
                                 owner="client.a", cookie="c1"))
            # a second owner is EBUSY
            with pytest.raises(RadosError):
                await io.execute("img", "lock", "lock",
                                 req(name="l", type="exclusive",
                                     owner="client.b", cookie="c2"))
            # someone else cannot unlock
            with pytest.raises(RadosError):
                await io.execute("img", "lock", "unlock",
                                 req(name="l", owner="client.b",
                                     cookie="c2"))
            info = json.loads(await io.execute(
                "img", "lock", "get_info", req(name="l")))
            assert info["type"] == "exclusive"
            assert len(info["lockers"]) == 1
            # break_lock evicts; then shared lockers coexist
            await io.execute("img", "lock", "break_lock",
                             req(name="l", locker="client.a",
                                 cookie="c1"))
            await io.execute("img", "lock", "lock",
                             req(name="l", type="shared",
                                 owner="client.b", cookie="c2"))
            await io.execute("img", "lock", "lock",
                             req(name="l", type="shared",
                                 owner="client.c", cookie="c3"))
            info = json.loads(await io.execute(
                "img", "lock", "get_info", req(name="l")))
            assert len(info["lockers"]) == 2
        finally:
            await cluster.stop()

    run(main())


def test_cls_writes_replicate_and_survive_failover():
    """State written by a class method recovers like any write."""
    async def main():
        cluster, io = await _cluster()
        try:
            req = json.dumps({"key": "n", "value": 7}).encode()
            await io.execute("obj", "numops", "add", req)
            pg = io.object_pg("obj")
            _acting, primary = \
                cluster.mon.osdmap.pg_to_acting_osds(pg)
            await cluster.kill_osd(primary)
            await cluster.wait_for_osd_down(primary)
            # the new primary serves the class state and methods
            out = await io.execute("obj", "numops", "add", req)
            assert float(out.decode()) == 14.0
        finally:
            await cluster.stop()

    run(main())

"""Packed bitmatrix encode-service tier + the sub-chunk op fast lane.

The bitmatrix family now batches on the hinfo write path (N objects'
regions packed into ONE native XOR-tape arena —
ec_util._encode_many_bitmatrix), gated by an arrival-density router
(a COLD bucket — sparse arrivals — encodes inline on the caller, no
off-loop hop; dense arrivals pool into packed tape runs), and
sub-chunk client ops skip the scheduler queue / objlock coroutine
round trips via scheduler.try_acquire + _ObjLock.try_acquire.  This
file pins the edge cases: the hot/cold router itself, ragged last
object in a packed batch, mixed-size bucket spill across flushes,
cancellation of one request mid-batch (the other futures still
resolve), the fast lane preserving mClock admission accounting (tag
charges identical to run()'s fast grant, over-limit classes
refused), _ObjLock FIFO/cancellation semantics, and the
CEPH_TPU_OP_FAST_LANE / CEPH_TPU_NATIVE_XSCHED kill switches.
"""

from __future__ import annotations

import asyncio
import time
import types

import numpy as np
import pytest

from ceph_tpu.ec import xsched
from ceph_tpu.ec.registry import create_erasure_code
from ceph_tpu.osd import daemon as osd_daemon
from ceph_tpu.osd import ec_util
from ceph_tpu.osd.encode_service import EncodeService
from ceph_tpu.osd.osdmap import TYPE_ERASURE, TYPE_REPLICATED
from ceph_tpu.osd.scheduler import MClockScheduler

RNG = np.random.default_rng(0xBA7C)

NATIVE = xsched.native_available()
needs_native = pytest.mark.skipif(
    not NATIVE, reason="native xor_sched executor not built")

K, W, PS = 4, 8, 512
CHUNK = W * PS                    # single-block chunks: packable
WIDTH = K * CHUNK


def _codec():
    return create_erasure_code(
        {"plugin": "ec_jax", "technique": "liber8tion", "k": str(K),
         "m": "2", "w": str(W), "packetsize": str(PS), "tpu": "false"})


def _sinfo():
    return ec_util.StripeInfo(K, WIDTH)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


def _payload(stripes=1):
    return bytes(RNG.integers(0, 256, stripes * WIDTH,
                              dtype=np.uint8))


def _check_item(sinfo, codec, d, got):
    shards, hinfo, crc = got
    ws, wh, wc = ec_util.encode_with_hinfo(sinfo, codec, d, range(6),
                                           logical_len=len(d))
    assert crc == wc
    assert hinfo.total_chunk_size == wh.total_chunk_size
    assert hinfo.cumulative_shard_hashes == wh.cumulative_shard_hashes
    for i in range(6):
        assert bytes(shards[i]) == bytes(ws[i]), i


# -- the packed bucket through the service -----------------------------


@needs_native
def test_bitmatrix_bucket_batches_and_stays_bit_exact():
    """Concurrent same-profile hinfo encodes of a bitmatrix codec
    batch through the packed native tape tier — far fewer tape runs
    than requests — and every result matches the inline path."""
    codec, sinfo = _codec(), _sinfo()
    bufs = [_payload() for _ in range(24)]

    async def main():
        # a generous window keeps the burst's intra-gap EWMA hot;
        # flushes come from the idle/completion hooks, not the timer
        svc = EncodeService(window_ms=50)
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                    logical_len=len(b))
              for b in bufs))
        st = svc.stats()
        await svc.stop()
        return outs, st

    xsched.reset_stats()
    outs, st = run(main())
    xs = xsched.stats()
    # the burst leader finds a cold bucket and stays inline (the
    # arrival-density router); everything behind it batches
    assert st["inline"] == st["inline_cold"] <= 2
    assert st["batched"] == 24 - st["inline"]
    assert st["batches"] >= 1
    # the whole point: one tape run per FLUSH, not per object (the
    # per-item oracle encodes below add their own runs, so sample
    # now; inline_cold requests run one native exec each)
    assert xs["exec_native"] <= st["batches"] + st["inline_cold"]
    for b, got in zip(bufs, outs):
        _check_item(sinfo, codec, b, got)


@needs_native
def test_cold_bucket_inlines_hot_burst_batches():
    """The arrival-density router: sparse singleton encodes never pay
    the off-loop batch hop (inline_cold moves, zero flushes), while a
    concurrent burst re-heats the bucket and rides the packed tier."""
    codec, sinfo = _codec(), _sinfo()

    async def main():
        svc = EncodeService(window_ms=5)
        for _ in range(3):      # gaps ~4x the window: stays cold
            out = await svc.encode_with_hinfo(
                sinfo, codec, bufs_cold[0], range(6),
                logical_len=WIDTH)
            _check_item(sinfo, codec, bufs_cold[0], out)
            await asyncio.sleep(0.02)
        cold = dict(svc.stats())
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                    logical_len=len(b))
              for b in bufs_burst))
        st = svc.stats()
        await svc.stop()
        return cold, outs, st

    bufs_cold = [_payload()]
    bufs_burst = [_payload() for _ in range(24)]
    cold, outs, st = run(main())
    assert cold["inline_cold"] == 3 and cold["batches"] == 0
    # the EWMA needs a few dense gaps to cross back under the window,
    # so a cold->hot transition leaks a handful of inline leaders —
    # but the bulk of the burst must batch
    assert st["batched"] >= 16
    assert st["batches"] >= 1
    assert st["batched"] + st["inline_cold"] == 27
    for b, got in zip(bufs_burst, outs):
        _check_item(sinfo, codec, b, got)


@needs_native
def test_ragged_last_object_in_packed_batch():
    """A packed batch with mixed per-object stripe counts — including
    a single-stripe ragged last object behind multi-stripe ones —
    packs into one arena and stays bit-exact per item."""
    codec, sinfo = _codec(), _sinfo()
    bufs = [_payload(s) for s in (2, 1, 3, 1)]

    async def main():
        svc = EncodeService(window_ms=20)
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                    logical_len=len(b) - 3)
              for b in bufs))
        st = svc.stats()
        await svc.stop()
        return outs, st

    outs, st = run(main())
    assert st["batched"] + st["inline_cold"] == 4
    assert st["batched"] >= 2, "no packed batch formed"
    for b, (shards, hinfo, crc) in zip(bufs, outs):
        ws, wh, wc = ec_util.encode_with_hinfo(
            sinfo, codec, b, range(6), logical_len=len(b) - 3)
        assert crc == wc
        assert hinfo.total_chunk_size == wh.total_chunk_size
        assert hinfo.cumulative_shard_hashes == \
            wh.cumulative_shard_hashes
        for i in range(6):
            assert bytes(shards[i]) == bytes(ws[i])


@needs_native
def test_mixed_size_bucket_spill_flushes_early():
    """Mixed-size requests overflowing the byte budget spill into
    MULTIPLE flushes (early flush on max_batch_bytes) — every flush
    packs its own arena and all results stay exact."""
    codec, sinfo = _codec(), _sinfo()
    sizes = (1, 4, 1, 2, 4, 1, 3, 1)
    bufs = [_payload(s) for s in sizes]

    async def main():
        svc = EncodeService(window_ms=50, max_batch_bytes=4 * WIDTH,
                            max_queue_bytes=64 * WIDTH)
        outs = await asyncio.gather(
            *(svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                    logical_len=len(b))
              for b in bufs))
        st = svc.stats()
        await svc.stop()
        return outs, st

    outs, st = run(main())
    assert st["batched"] + st["inline_cold"] == len(bufs)
    assert st["batches"] >= 2, "byte budget never spilled a flush"
    for b, got in zip(bufs, outs):
        _check_item(sinfo, codec, b, got)


@needs_native
def test_cancel_one_mid_batch_others_resolve():
    """Cancelling one request while its batch accumulates must not
    poison the flush: the cancelled caller sees CancelledError, every
    other future resolves bit-exact."""
    codec, sinfo = _codec(), _sinfo()
    bufs = [_payload() for _ in range(6)]

    async def main():
        svc = EncodeService(window_ms=60_000)
        tasks = [asyncio.ensure_future(
            svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                  logical_len=len(b)))
            for b in bufs]
        await asyncio.sleep(0)
        tasks[2].cancel()
        await svc.stop()
        return await asyncio.gather(*tasks, return_exceptions=True)

    outs = run(main())
    assert isinstance(outs[2], asyncio.CancelledError)
    for idx, (b, got) in enumerate(zip(bufs, outs)):
        if idx == 2:
            continue
        _check_item(sinfo, codec, b, got)


@needs_native
def test_plain_encode_and_decode_stay_inline_for_bitmatrix():
    """The packed tape tier exists only for the hinfo write path:
    plain encode and decode of a bitmatrix codec keep the inline
    tiers (which are themselves native underneath) — and match."""
    codec, sinfo = _codec(), _sinfo()
    buf = _payload(2)

    async def main():
        svc = EncodeService()
        enc = await svc.encode(sinfo, codec, buf, range(6))
        dec = await svc.decode(sinfo, codec,
                               {i: enc[i] for i in (1, 2, 3, 5)})
        st = svc.stats()
        await svc.stop()
        return enc, dec, st

    enc, dec, st = run(main())
    assert st["batched"] == 0 and st["inline"] == 2
    ref = ec_util.encode(sinfo, codec, buf, range(6))
    assert all(bytes(enc[i]) == bytes(ref[i]) for i in range(6))
    assert dec == buf


def test_native_kill_switch_keeps_service_inline(monkeypatch):
    """CEPH_TPU_NATIVE_XSCHED=0 closes the batching gate for the
    bitmatrix family entirely — requests run inline, bit-identically
    (the host schedule tier underneath)."""
    monkeypatch.setenv("CEPH_TPU_NATIVE_XSCHED", "0")
    codec, sinfo = _codec(), _sinfo()
    buf = _payload()

    async def main():
        svc = EncodeService()
        out = await svc.encode_with_hinfo(sinfo, codec, buf, range(6),
                                          logical_len=len(buf))
        st = svc.stats()
        await svc.stop()
        return out, st

    out, st = run(main())
    assert st["inline"] == 1 and st["batched"] == 0
    _check_item(sinfo, codec, buf, out)


# -- the scheduler fast lane: mClock accounting preserved --------------


def test_fast_lane_grants_slots_and_counts():
    s = MClockScheduler(max_concurrent=2)
    assert s.try_acquire("client", 1.0)
    assert s.try_acquire("client", 1.0)
    assert not s.try_acquire("client", 1.0), "slot bound ignored"
    st = s.stats()
    assert st["in_flight"] == 2
    assert st["granted"]["client"] == 2
    assert st["fast_lane"]["client"] == 2
    s.release()
    s.release()
    assert s.stats()["in_flight"] == 0
    assert s.try_acquire("client", 1.0)
    s.release()


def test_fast_lane_charges_mclock_tags_like_enqueue():
    """The fast grant advances the class's R/P/L tags by exactly the
    _enqueue + _charge_limit formula — fairness accounting cannot
    drift between the fast lane and the queued path."""
    r, w, l = 2.0, 0.5, 4.0
    s = MClockScheduler(profiles={"cls": (r, w, l)})
    cost = 4.0
    t0 = time.monotonic()
    assert s.try_acquire("cls", cost)
    t1 = time.monotonic()
    # first grant: R floors at now (no banked credit), P and L
    # advance from now by cost/w and cost/l
    assert t0 <= s._last_r["cls"] <= t1
    assert t0 + cost / w <= s._last_p["cls"] <= t1 + cost / w
    assert t0 + cost / l <= s._last_l["cls"] <= t1 + cost / l
    s.release()
    # steady state (limit 0 so the second grant is admitted): R and P
    # advance from their prior tags by exactly cost/r and cost/w
    s2 = MClockScheduler(profiles={"cls": (r, w, 0.0)})
    assert s2.try_acquire("cls", cost)
    r1, p1 = s2._last_r["cls"], s2._last_p["cls"]
    s2.release()
    assert s2.try_acquire("cls", cost)
    assert s2._last_r["cls"] == pytest.approx(r1 + cost / r)
    assert s2._last_p["cls"] == pytest.approx(p1 + cost / w)
    s2.release()


def test_fast_lane_refuses_over_limit_class():
    """An over-limit class cannot launder QoS through the fast lane:
    the second immediate acquire is refused (it must queue behind its
    L-tag) and the refusal consumes no slot and no counters."""
    s = MClockScheduler(profiles={"lim": (0.0, 1.0, 1.0)})
    assert s.try_acquire("lim", 2.0)    # L-tag lands 2s in the future
    s.release()
    assert not s.try_acquire("lim", 2.0)
    st = s.stats()
    assert st["in_flight"] == 0
    assert st["fast_lane"]["lim"] == 1
    assert st["granted"]["lim"] == 1


def test_fast_lane_refused_while_work_is_queued():
    """Queued work keeps strict priority: the fast lane only wins on
    a completely idle scheduler (same condition as run()'s fast
    grant)."""
    s = MClockScheduler(max_concurrent=1)

    async def main():
        release = asyncio.Event()

        async def body():
            await release.wait()
            return "ran"

        first = asyncio.ensure_future(s.run("client", 1.0, body))
        second = asyncio.ensure_future(s.run("client", 1.0, body))
        for _ in range(10):
            await asyncio.sleep(0)
        # one op holds the slot, one is queued: both conditions refuse
        assert not s.try_acquire("client", 1.0)
        release.set()
        assert await asyncio.gather(first, second) == ["ran", "ran"]
        await s.stop()

    run(main())


# -- per-mClock-class arrival density (the hot/cold router) ------------


def test_current_class_rides_both_grant_paths():
    """scheduler.current_class() reports the running op's class under
    both the fast grant and the queued grant, and resets after."""
    from ceph_tpu.osd import scheduler as sched_mod

    async def main():
        s = MClockScheduler(max_concurrent=1)
        seen = []

        async def probe():
            seen.append(sched_mod.current_class())

        assert sched_mod.current_class() == ""
        await s.run("background_recovery", 1.0, probe)  # fast grant
        hold = asyncio.Event()

        async def holder():
            await hold.wait()

        first = asyncio.ensure_future(s.run("client", 1.0, holder))
        await asyncio.sleep(0)
        queued = asyncio.ensure_future(s.run("client", 1.0, probe))
        await asyncio.sleep(0)
        hold.set()
        await asyncio.gather(first, queued)
        assert sched_mod.current_class() == ""
        await s.stop()
        return seen

    assert run(main()) == ["background_recovery", "client"]


@needs_native
def test_cold_router_tracks_arrival_density_per_class():
    """A dense recovery wave heating the bucket must not drag sparse
    client singletons onto the off-loop batch hop: arrival density is
    per mClock class, so the client trickle stays inline_cold while
    the recovery burst batches through the packed tier."""
    codec, sinfo = _codec(), _sinfo()
    sched = MClockScheduler(
        profiles={"background_recovery": (0.0, 1.0, 0.0),
                  "client": (0.0, 1.0, 0.0)},
        max_concurrent=32)
    bufs = [_payload() for _ in range(16)]

    async def main():
        svc = EncodeService(window_ms=50)

        def enc(b):
            return svc.encode_with_hinfo(sinfo, codec, b, range(6),
                                         logical_len=len(b))

        outs = await asyncio.gather(
            *(sched.run("background_recovery", 1.0, lambda b=b: enc(b))
              for b in bufs))
        hot = dict(svc.stats())
        assert hot["batched"] >= 8, "recovery burst never batched"
        # gaps ~4x the window: a cold trickle in ANY class — but the
        # bucket-global EWMA of old would have inherited the burst's
        # dense gaps and routed these through the batch hop
        for _ in range(3):
            out = await sched.run("client", 1.0,
                                  lambda: enc(bufs[0]))
            _check_item(sinfo, codec, bufs[0], out)
            await asyncio.sleep(0.02)
        st = svc.stats()
        await svc.stop()
        return outs, hot, st

    outs, hot, st = run(main())
    assert st["inline_cold"] - hot["inline_cold"] == 3, \
        "client trickle lost its per-class cold routing"
    for b, got in zip(bufs, outs):
        _check_item(sinfo, codec, b, got)


# -- _ObjLock: the sync-acquire objlock half ---------------------------


def test_objlock_try_acquire_only_when_free_with_no_waiters():
    lk = osd_daemon._ObjLock()

    async def main():
        assert lk.try_acquire()
        waiter = asyncio.ensure_future(lk.acquire())
        await asyncio.sleep(0)
        assert not lk.try_acquire()          # held
        lk.release()
        # woken but not yet resumed: FIFO priority keeps the sync
        # path out until the waiter actually takes the lock
        assert not lk.try_acquire()
        assert await waiter
        assert lk.locked()
        lk.release()
        assert lk.try_acquire()
        lk.release()

    run(main())


def test_objlock_cancelled_woken_waiter_passes_wakeup_on():
    lk = osd_daemon._ObjLock()

    async def main():
        assert lk.try_acquire()
        w1 = asyncio.ensure_future(lk.acquire())
        w2 = asyncio.ensure_future(lk.acquire())
        await asyncio.sleep(0)
        lk.release()        # wakes w1
        w1.cancel()         # ... which dies before resuming
        with pytest.raises(asyncio.CancelledError):
            await w1
        assert await w2     # the wakeup moved on instead of vanishing
        assert lk.locked()
        lk.release()

    run(main())


def test_objlock_release_unlocked_raises():
    with pytest.raises(RuntimeError):
        osd_daemon._ObjLock().release()


def test_objlockctx_try_enter_exit_sync_refcount_and_eviction():
    async def main():
        table: dict = {}
        entry = table.setdefault("oid", [osd_daemon._ObjLock(), 0])
        ctx = osd_daemon._ObjLockCtx(table, "oid", entry)
        assert ctx.try_enter()
        assert entry[1] == 1 and entry[0].locked()
        other = osd_daemon._ObjLockCtx(table, "oid", entry)
        assert not other.try_enter()         # contended: async path
        assert entry[1] == 1                 # refused = no refcount
        ctx.exit_sync()
        assert "oid" not in table            # idle entry evicted

    run(main())


# -- the daemon gate + kill switch -------------------------------------


def test_op_fast_lane_gate_and_kill_switch():
    sinfo = _sinfo()
    stub = types.SimpleNamespace(_op_fast_lane=True,
                                 _sinfo=lambda pid: sinfo)
    ok = osd_daemon.OSDDaemon._op_fast_lane_ok
    ec_pool = types.SimpleNamespace(type=TYPE_ERASURE, id=1)
    rep_pool = types.SimpleNamespace(type=TYPE_REPLICATED, id=2)
    assert ok(stub, ec_pool, CHUNK)          # fits one chunk
    assert not ok(stub, ec_pool, CHUNK + 1)  # bigger: queued path
    assert not ok(stub, rep_pool, 16)        # EC pools only
    stub._op_fast_lane = False               # CEPH_TPU_OP_FAST_LANE=0
    assert not ok(stub, ec_pool, 16)
    stub._op_fast_lane = True

    def boom(pid):
        raise KeyError(pid)

    stub._sinfo = boom                       # no profile: stay queued
    assert not ok(stub, ec_pool, 16)


# -- daemon end to end: sub-chunk writes ride lane + packed tier -------


@needs_native
def test_daemon_sub_chunk_writes_fast_lane_and_pack_end_to_end():
    """Small writes to a bitmatrix EC pool on a live cluster take the
    sub-chunk fast lane (scheduler fast_lane counters move, mClock
    granted accounting includes them) and read back bit-exact."""
    from cluster_helpers import Cluster

    EC = {"plugin": "ec_jax", "technique": "liber8tion",
          "k": str(K), "m": "2", "w": str(W), "packetsize": str(PS),
          "crush-failure-domain": "osd", "stripe_unit": str(CHUNK)}
    n_objs = 10
    payloads = [RNG.integers(0, 256, 1 << 10, dtype=np.uint8).tobytes()
                for _ in range(n_objs)]

    async def main():
        cluster = Cluster(num_osds=6)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool("bmx", profile=EC,
                                                pg_num=8)
            io = cluster.client.open_ioctx("bmx")
            for i in range(n_objs):
                await io.write_full(f"o{i}", payloads[i])
            reads = [await io.read(f"o{i}") for i in range(n_objs)]
            scheds = [osd.scheduler.stats()
                      for osd in cluster.osds.values()]
            return reads, scheds
        finally:
            await cluster.stop()

    reads, scheds = run(main())
    assert reads == payloads
    fast = sum(sum(s["fast_lane"].values()) for s in scheds)
    assert fast > 0, "no op rode the sub-chunk fast lane"
    for s in scheds:
        for cls, n in s["fast_lane"].items():
            assert s["granted"].get(cls, 0) >= n

"""S3 presigned URLs (query-string sigv4 — the AWSv4 query-auth /
`aws s3 presign` role): credential-less HTTP clients use a minted URL
until it expires; tampering and expiry are rejected."""

import asyncio
import shutil
import subprocess

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import S3Frontend, presign_url

from test_s3_http import ACCESS, SECRET, MiniS3, _stack


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


async def _raw_http(addr: str, method: str, target: str,
                    body: bytes = b""):
    """A dumb HTTP client with NO credentials at all."""
    host, port = addr.rsplit(":", 1)
    r, w = await asyncio.open_connection(host, int(port),
                                         limit=8 << 20)
    req = (f"{method} {target} HTTP/1.1\r\n"
           f"Host: {addr}\r\nContent-Length: {len(body)}\r\n"
           f"Connection: close\r\n\r\n")
    w.write(req.encode() + body)
    await w.drain()
    status = int((await r.readline()).split()[1])
    hdrs = {}
    while True:
        line = await r.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode().partition(":")
        hdrs[k.strip().lower()] = v.strip()
    data = await r.read()
    w.close()
    return status, hdrs, data


def test_presigned_get_put_expiry_and_tamper():
    async def main():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            s3 = MiniS3(addr)
            st, _, _ = await s3.request("PUT", "/share")
            assert st == 200
            st, _, _ = await s3.request(
                "PUT", "/share/doc.txt", body=b"presigned payload")
            assert st == 200
            # presigned GET: a credential-less client fetches it
            url = presign_url("GET", addr, "/share/doc.txt",
                              ACCESS, SECRET, expires=300)
            target = url[len(f"http://{addr}"):]
            st, _, body = await _raw_http(addr, "GET", target)
            assert st == 200 and body == b"presigned payload"
            # presigned PUT uploads without credentials too
            url = presign_url("PUT", addr, "/share/up.bin",
                              ACCESS, SECRET, expires=300)
            target = url[len(f"http://{addr}"):]
            st, _, _ = await _raw_http(addr, "PUT", target,
                                       body=b"uploaded!")
            assert st == 200
            st, _, body = await s3.request("GET", "/share/up.bin")
            assert st == 200 and body == b"uploaded!"
            # tampered signature rejected
            bad = target.replace("X-Amz-Signature=",
                                 "X-Amz-Signature=0000")
            st, _, body = await _raw_http(addr, "GET", bad)
            assert st == 403, (st, body)
            # expired URL rejected: expires=1, then outlive it
            url = presign_url("GET", addr, "/share/doc.txt",
                              ACCESS, SECRET, expires=1)
            target = url[len(f"http://{addr}"):]
            await asyncio.sleep(1.2)
            st, _, body = await _raw_http(addr, "GET", target)
            assert st == 403 and b"expired" in body.lower(), (st,
                                                              body)
            # out-of-range expiry (beyond the 7-day cap) rejected
            url = presign_url("GET", addr, "/share/doc.txt",
                              ACCESS, SECRET, expires=999999999)
            target = url[len(f"http://{addr}"):]
            st, _, _ = await _raw_http(addr, "GET", target)
            assert st == 403
            # keys with spaces survive the canonical-URI encoding
            st, _, _ = await s3.request("PUT", "/share/my%20doc.txt",
                                        body=b"spaced out")
            assert st == 200
            url = presign_url("GET", addr, "/share/my doc.txt",
                              ACCESS, SECRET, expires=300)
            target = url[len(f"http://{addr}"):]
            st, _, body = await _raw_http(addr, "GET", target)
            assert st == 200 and body == b"spaced out", (st, body)
            # stock curl leg: an INDEPENDENT client consumes the URL
            if shutil.which("curl"):
                url = presign_url("GET", addr, "/share/doc.txt",
                                  ACCESS, SECRET, expires=300)
                proc = await asyncio.create_subprocess_exec(
                    "curl", "-s", url,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE)
                out, err = await asyncio.wait_for(
                    proc.communicate(), 30)
                assert out == b"presigned payload", (out, err)
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()
    run(main())

"""Stripe-level EC read-modify-write tier.

Shape parity: the reference's ECBackend RMW pipeline
(src/osd/ECBackend.cc:1858-2087) + ExtentCache, tested the
test_ec_transaction/store_test way: random partial overwrites checked
against a full-object oracle, and transfer-volume assertions proving a
small write/read moves O(stripe), not O(object)."""

import asyncio

import numpy as np
import pytest

from ceph_tpu.osd.osdmap import PgId

from cluster_helpers import Cluster

EC21 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "1", "crush-failure-domain": "osd",
        "tpu": "false"}
EC83 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "8", "m": "3", "crush-failure-domain": "osd",
        "tpu": "false"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


def _primary_of(cluster, pool_name: str, oid: str):
    osdmap = cluster.mon.osdmap
    pool = [p for p in osdmap.pools.values() if p.name == pool_name][0]
    from ceph_tpu.ops.rjenkins import ceph_str_hash_rjenkins

    ps = ceph_str_hash_rjenkins(oid.encode())
    pg = pool.raw_pg_to_pg(PgId(pool.id, ps))
    _acting, primary = osdmap.pg_to_acting_osds(pg)
    return cluster.osds[primary]


def test_random_offset_overwrites_match_oracle():
    """Unaligned head/tail overwrites + extends vs a bytearray model."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC21, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            rng = np.random.default_rng(11)
            model = bytearray()
            await io.write_full(
                "obj", bytes(rng.integers(0, 256, 50_000,
                                          dtype=np.uint8)))
            model[:] = await io.read("obj")
            for step in range(25):
                off = int(rng.integers(0, 70_000))
                ln = int(rng.integers(1, 9_000))
                payload = bytes(rng.integers(0, 256, ln,
                                             dtype=np.uint8))
                await io.write("obj", payload, off)
                if off + ln > len(model):
                    model.extend(bytes(off + ln - len(model)))
                model[off:off + ln] = payload
                if step % 5 == 4:
                    got = await io.read("obj")
                    assert got == bytes(model), f"diverged at {step}"
            assert await io.read("obj") == bytes(model)
            # ranged reads agree with the oracle too
            for _ in range(10):
                off = int(rng.integers(0, len(model)))
                ln = int(rng.integers(1, 5_000))
                got = await io.read("obj", offset=off, length=ln)
                assert got == bytes(model[off:off + ln])
        finally:
            await cluster.stop()

    run(main())


def test_small_write_moves_stripes_not_objects():
    """A 100-byte overwrite of a 4 MiB EC 8+3 object transfers
    O(stripe) sub-op bytes and ONE encode dispatch — not O(object)."""
    async def main():
        cluster = Cluster(num_osds=12, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC83, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = bytes(np.random.default_rng(1).integers(
                0, 256, 4 << 20, dtype=np.uint8))
            await io.write_full("big", obj)
            prim = _primary_of(cluster, "ec", "big")
            stripe_w = 8 * 4096
            base = dict(prim.perf)
            await io.write("big", b"x" * 100, 1_000_003)
            moved = (prim.perf["subread_bytes"] - base["subread_bytes"]
                     + prim.perf["subwrite_bytes"]
                     - base["subwrite_bytes"])
            enc = prim.perf["encode_dispatches"] \
                - base["encode_dispatches"]
            # one stripe touched: reads k ranges + writes k+m ranges,
            # each ~stripe/k — generous bound far below the 4 MiB object
            assert moved < 6 * stripe_w, f"moved {moved} bytes"
            assert enc == 1
            got = await io.read("big", offset=1_000_000, length=200)
            want = obj[1_000_000:1_000_003] + b"x" * 100 + \
                obj[1_000_103:1_000_200]
            assert got == want
        finally:
            await cluster.stop()

    run(main())


def test_ranged_read_moves_stripes_not_objects():
    async def main():
        cluster = Cluster(num_osds=12, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC83, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = bytes(np.random.default_rng(2).integers(
                0, 256, 4 << 20, dtype=np.uint8))
            await io.write_full("big", obj)
            prim = _primary_of(cluster, "ec", "big")
            stripe_w = 8 * 4096
            base = prim.perf["subread_bytes"]
            got = await io.read("big", offset=2_000_000, length=4096)
            assert got == obj[2_000_000:2_004_096]
            moved = prim.perf["subread_bytes"] - base
            assert moved < 4 * stripe_w, f"moved {moved} bytes"
        finally:
            await cluster.stop()

    run(main())


def test_extent_cache_skips_rereads():
    """Back-to-back small writes to the same stripe: the second one is
    served from the primary's extent cache (zero sub-read bytes)."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "ec", profile=EC21, pg_num=8)
            io = cluster.client.open_ioctx("ec")
            obj = bytes(np.random.default_rng(3).integers(
                0, 256, 200_000, dtype=np.uint8))
            await io.write_full("obj", obj)
            prim = _primary_of(cluster, "ec", "obj")
            await io.write("obj", b"a" * 50, 10_000)   # warms the cache
            base = prim.perf["subread_bytes"]
            await io.write("obj", b"b" * 50, 10_100)   # same stripe
            assert prim.perf["subread_bytes"] == base, "cache miss"
            got = await io.read("obj", offset=9_990, length=200)
            model = bytearray(obj)
            model[10_000:10_050] = b"a" * 50
            model[10_100:10_150] = b"b" * 50
            assert got == bytes(model[9_990:10_190])
        finally:
            await cluster.stop()

    run(main())

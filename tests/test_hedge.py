"""Hedged-read tier: per-peer latency EWMAs, the hedged first-k
gather primitive, cancellation safety (no leaked tasks, no corrupted
connection framing), survivor-set ranking, and the live-cluster
integration under an injected slow OSD.

The core claims under test:
1. a hedged gather completes from the first k DISTINCT arrivals and
   cancels stragglers without leaking a single asyncio task;
2. hedged and unhedged reads are bit-identical (hedging changes WHEN
   enough arrivals exist, never what is decoded from them);
3. a sub-read cancelled mid-send can never corrupt connection framing
   (frame seqs are allocated under the send lock);
4. slow peers are learned (EWMA), ranked last, and re-earn trust by
   decay; faulting peers rank last via their breaker.
"""

import asyncio
import os
import time

import numpy as np
import pytest

from ceph_tpu.osd import ec_util
from ceph_tpu.osd.hedge import HedgeTracker, PeerStats

from cluster_helpers import Cluster

EC_PROFILE = {"plugin": "ec_jax", "technique": "reed_sol_van",
              "k": "2", "m": "2", "crush-failure-domain": "osd"}


def run(coro, timeout=240):
    asyncio.run(asyncio.wait_for(coro, timeout))


# -- the latency model -----------------------------------------------------


def test_ewma_learns_and_decays_toward_prior():
    now = [0.0]
    st = PeerStats(3, alpha=0.5, halflife=10.0, prior=0.010,
                   clock=lambda: now[0])
    for _ in range(20):
        now[0] += 0.001
        st.observe(0.200)
    assert st.ewma > 0.15          # learned: this peer is slow
    assert st.p95() >= st.ewma
    # idle for two half-lives: trust is re-earned toward the prior
    now[0] += 20.0
    assert st.ewma_now() < 0.06
    now[0] += 200.0
    assert abs(st.ewma_now() - 0.010) < 0.002


def test_failures_trip_breaker_and_rank_last():
    now = [0.0]
    tr = HedgeTracker("t", clock=lambda: now[0])
    for osd, rtt in ((1, 0.001), (2, 0.005), (3, 0.002)):
        for _ in range(3):
            now[0] += 0.01
            tr.observe(osd, rtt)
    # peer 1 is fastest...
    order = sorted([1, 2, 3], key=tr.rank_key)
    assert order == [1, 3, 2]
    # ...until its sub-reads fault: breaker degrades it to rank-last
    for _ in range(4):
        now[0] += 0.01
        tr.observe(1, 5.0, ok=False)
    assert tr.peer(1).degraded()
    order = sorted([1, 2, 3], key=tr.rank_key)
    assert order[-1] == 1
    # backoff expiry restores normal (EWMA) ranking — trust re-earned
    now[0] += 3600.0
    assert not tr.peer(1).degraded()
    # ...but a STILL-dead peer re-trips on its next failure (the
    # expired-open sub-read plays the half-open probe), with an
    # escalated backoff — it can never be reported healthy forever
    now[0] += 0.01
    tr.observe(1, 5.0, ok=False)
    assert tr.peer(1).degraded()
    # and one genuine success re-closes it for good
    now[0] += 3600.0
    tr.observe(1, 0.002, ok=True)
    assert not tr.peer(1).degraded()
    assert tr.peer(1).breaker.state == "closed"


def test_censored_cancel_never_teaches_fast():
    """A straggler cancelled the instant faster peers answer must NOT
    learn the winners' latency (it would rank among the fastest and
    tax every later read); only elapsed time EXCEEDING its estimate
    ratchets the model up.  The breaker sees neither direction — a
    lost race is not evidence of peer health."""
    now = [0.0]
    st = PeerStats(7, alpha=0.5, halflife=1e9, prior=0.010,
                   clock=lambda: now[0])
    st.observe_censored(0.001)     # cancelled at the winner's 1 ms
    assert st.ewma == 0.010 and st.samples == 0
    st.observe_censored(0.050)     # outlived its hedge mark
    assert st.ewma > 0.010 and st.samples == 1
    stats = st.breaker.stats()
    assert stats["successes"] == 0 and stats["failures"] == 0


def test_spread_escalates_delta():
    tr = HedgeTracker("t", {"osd_hedge_delta": 1,
                            "osd_hedge_spread_escalate": 4.0})
    for _ in range(4):
        tr.observe(1, 0.001)
        tr.observe(2, 0.200)
    assert tr.spread() > 4.0
    assert tr.effective_delta() == 2
    assert tr.counters["escalations"] >= 1


# -- the gather primitive --------------------------------------------------


def _sub(shard, delay, ok=True):
    async def job():
        await asyncio.sleep(delay)
        if not ok:
            return [], False
        return [(shard, bytes([shard % 256]), {})], True
    return job


def _distinct(results):
    return {c[0] for sub, _ok in results for c in sub}


def test_gather_first_k_completes_and_cancels_stragglers():
    async def main():
        tr = HedgeTracker("t", {"osd_hedge_delay_floor_ms": 5.0})
        delays = {0: 0.001, 1: 0.001, 2: 0.001, 3: 1.0, 4: 1.0,
                  5: 0.001}
        jobs = [(o, _sub(o, delays[o])) for o in range(6)]
        t0 = time.perf_counter()
        results, ran_all = await tr.gather(
            jobs, need=4,
            sufficient=lambda rs: len(_distinct(rs)) >= 4,
            failed=lambda r: not r[0])
        dt = time.perf_counter() - t0
        assert len(_distinct(results)) >= 4
        assert dt < 0.5, "gather waited for the 1s stragglers"
        assert ran_all is False  # early exit cannot claim completeness
        assert tr.counters["hedges_fired"] >= 1
        assert tr.counters["cancelled_subreads"] >= 1
        # the no-leak guarantee: nothing spawned survives the gather
        leaked = [t for t in asyncio.all_tasks()
                  if t is not asyncio.current_task()
                  and t.get_name().startswith("hedge:")
                  and not t.done()]
        assert not leaked
        # cancelled stragglers fed their elapsed time: the model
        # learned they are at least hedge-delay slow
        assert tr.peer(3).samples + tr.peer(4).samples >= 1

    run(main())


def test_gather_failed_result_recruits_spare():
    async def main():
        tr = HedgeTracker("t", {"osd_hedge_delta": 0,
                                "osd_hedge_delay_floor_ms": 500.0})
        # delta=0: exactly k launch; peer 1 faults fast, and the spare
        # (peer 2) must be recruited IMMEDIATELY by the failed
        # predicate, not after the 500 ms hedge timer
        jobs = [(0, _sub(0, 0.001)), (1, _sub(1, 0.002, ok=False)),
                (2, _sub(2, 0.001))]
        t0 = time.perf_counter()
        results, _ran = await tr.gather(
            jobs, need=2,
            sufficient=lambda rs: len(_distinct(rs)) >= 2,
            failed=lambda r: not r[0])
        assert len(_distinct(results)) >= 2
        assert time.perf_counter() - t0 < 0.4

    run(main())


def test_gather_widens_on_insufficient_non_failed_results():
    """Results the `failed` predicate accepts but the sufficiency
    predicate rejects (hinfo-corrupt payloads, version-divergent
    shards) must WIDEN the fan-out to the remaining ranked spares —
    not strand them unqueried and fail a readable object."""
    async def main():
        tr = HedgeTracker("t", {"osd_hedge_delta": 1,
                                "osd_hedge_delay_floor_ms": 500.0})
        # jobs 0-2 all return (divergent copies of) shard 0; only the
        # never-initially-launched job 3 holds the second distinct
        # shard.  need=2 + delta=1 launches 0-2; all complete fast,
        # non-failed, insufficient — the gather must recruit job 3
        # well before the 500 ms hedge timer could
        jobs = [(o, _sub(0, 0.001)) for o in range(3)] + \
            [(3, _sub(1, 0.001))]
        t0 = time.perf_counter()
        results, ran_all = await tr.gather(
            jobs, need=2,
            sufficient=lambda rs: len(_distinct(rs)) >= 2,
            failed=lambda r: not r[0])
        assert len(_distinct(results)) >= 2
        assert time.perf_counter() - t0 < 0.4
        assert ran_all is True  # every job ran in the end

    run(main())


def test_gather_runs_all_when_insufficient():
    """An absent object: every shard answers definitively-empty; the
    gather must run EVERY job and report completeness."""
    async def main():
        tr = HedgeTracker("t")

        def empty(shard):
            async def job():
                await asyncio.sleep(0.001)
                return [], True
            return job

        jobs = [(o, empty(o)) for o in range(5)]
        results, ran_all = await tr.gather(
            jobs, need=3, sufficient=lambda rs: False,
            failed=lambda r: not r[0])
        assert len(results) == 5
        assert ran_all is True

    run(main())


def test_gather_all_shard_modes():
    """need=None and the kill switch both run every job (bare-gather
    parity), with managed task names."""
    async def main():
        jobs = [(o, _sub(o, 0.001)) for o in range(4)]
        tr = HedgeTracker("t")
        results, ran_all = await tr.gather(jobs)  # need=None
        assert len(results) == 4 and ran_all
        os.environ["CEPH_TPU_HEDGE"] = "0"
        try:
            tr2 = HedgeTracker("t")
            assert not tr2.enabled
            results, ran_all = await tr2.gather(
                [(o, _sub(o, 0.001)) for o in range(4)], need=2,
                sufficient=lambda rs: len(_distinct(rs)) >= 2)
            assert len(results) == 4 and ran_all
            assert tr2.counters["hedged_gathers"] == 0
        finally:
            os.environ.pop("CEPH_TPU_HEDGE", None)

    run(main())


def test_gather_propagates_caller_cancellation():
    async def main():
        tr = HedgeTracker("t")
        jobs = [(o, _sub(o, 5.0)) for o in range(4)]
        task = asyncio.get_running_loop().create_task(tr.gather(
            jobs, need=2,
            sufficient=lambda rs: len(_distinct(rs)) >= 2))
        await asyncio.sleep(0.05)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        leaked = [t for t in asyncio.all_tasks()
                  if t is not asyncio.current_task()
                  and t.get_name().startswith("hedge:")
                  and not t.done()]
        assert not leaked
        # external cancellation charges nobody: the elapsed time is
        # the canceller's impatience, not the peers' latency
        assert all(st.samples == 0 for st in tr.peers.values())

    run(main())


# -- survivor-set ranking --------------------------------------------------


class _FakeCodec:
    """minimum_to_decode needing `require` in the set (widening)."""

    def __init__(self, k, require=None):
        self.k = k
        self.require = require

    def chunk_index(self, i):
        return i

    def minimum_to_decode(self, want, have):
        if self.require is not None and self.require not in have:
            raise ValueError(f"need shard {self.require}")
        out = set()
        for s in sorted(have):
            if len(out) >= self.k:
                break
            out.add(s)
        return out


def test_fastest_survivors_data_first_then_rank():
    codec = _FakeCodec(2)
    rank = {5: 0, 4: 1, 3: 2, 2: 3, 1: 4, 0: 5}
    # all data shards present: the free all-data decode always wins —
    # EWMA rank must never trade a free interleave for a GF dispatch
    have = {s: bytes([s]) for s in range(6)}
    out = ec_util.fastest_survivors(codec, have, 2,
                                    prefer=lambda s: rank[s])
    assert set(out) == {0, 1}
    # one data shard missing: the FASTEST-ranked parity fills in
    have2 = {s: bytes([s]) for s in (0, 2, 3, 4, 5)}
    out2 = ec_util.fastest_survivors(codec, have2, 2,
                                     prefer=lambda s: rank[s])
    assert set(out2) == {0, 5}


def test_fastest_survivors_widens_and_raises():
    # the codec insists on (slow-ranked) shard 2: the preferred
    # subsets are infeasible and the helper widens until it joins
    codec = _FakeCodec(2, require=2)
    rank = {5: 0, 4: 1, 3: 2, 2: 3, 1: 4, 0: 5}
    have = {s: bytes([s]) for s in (0, 2, 3, 4, 5)}
    out = ec_util.fastest_survivors(codec, have, 2,
                                    prefer=lambda s: rank[s])
    assert 2 in out
    # infeasible even at the full set: the codec's error propagates
    with pytest.raises(ValueError):
        ec_util.fastest_survivors(
            _FakeCodec(2, require=9), have, 2)


# -- cancellation vs connection framing ------------------------------------


def test_cancelled_send_does_not_corrupt_framing():
    """A send cancelled while queued behind the connection send lock
    must not consume a frame seq: on a keyed connection the receiver
    enforces seq continuity, and a gapped seq kills the link (the
    failure mode hedged cancellation would hit constantly)."""
    from ceph_tpu.common import auth as auth_mod
    from ceph_tpu.msg import Messenger
    from ceph_tpu.msg.messages import MOSDOp, MOSDOpReply, OSDOp
    from ceph_tpu.osd.osdmap import PgId

    async def main():
        secret = auth_mod.generate_secret()
        server = Messenger("osd.0",
                           secret=auth_mod.parse_secret(secret))
        client = Messenger("client.1",
                           secret=auth_mod.parse_secret(secret))
        got = asyncio.Queue()

        async def server_dispatch(conn, msg):
            await conn.send(MOSDOpReply(msg.tid, 0, b"ok"))

        server.dispatcher = server_dispatch
        client.dispatcher = lambda c, m: got.put(m)
        addr = await server.bind()
        try:
            conn = await client.connect(addr)

            def op(tid):
                return MOSDOp(tid, "client.1", PgId(1, 0), "o",
                              [OSDOp("write", data=b"x")], 1)

            await conn.send(op(1))
            await asyncio.wait_for(got.get(), 5)
            # hold the send lock; a second send parks on it; cancel it
            # there — with seq allocated outside the lock this gapped
            # the stream and the NEXT frame killed the connection
            async with conn._send_lock:
                park = asyncio.get_running_loop().create_task(
                    conn.send(op(2)))
                await asyncio.sleep(0.05)
                park.cancel()
            try:
                await park
            except asyncio.CancelledError:
                pass
            await conn.send(op(3))
            reply = await asyncio.wait_for(got.get(), 5)
            assert reply.rc == 0
            assert not conn.closed, "framing corrupted by cancellation"
        finally:
            await client.shutdown()
            await server.shutdown()

    run(main())


# -- live cluster ----------------------------------------------------------


async def _placements(cluster, io, oids):
    prim = {}
    acting_of = {}
    for oid in oids:
        pg = io.object_pg(oid)
        acting, p = cluster.mon.osdmap.pg_to_acting_osds(pg)
        prim[oid] = p
        acting_of[oid] = acting
    return prim, acting_of


def test_hedged_reads_bit_exact_under_slow_osd():
    """One injected slow OSD on the sub-read path: hedged reads stay
    byte-identical, the primaries fire/win hedges and cancel
    stragglers cleanly, the hedge_status/perf surfaces report it, and
    no hedge task survives the workload."""
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "hp", EC_PROFILE, pg_num=8)
            io = cluster.client.open_ioctx("hp")
            payloads = {}
            for i in range(12):
                data = np.random.default_rng(900 + i).integers(
                    0, 256, 20_000 + 37 * i,
                    dtype=np.uint8).tobytes()
                await io.write_full(f"h{i}", data)
                payloads[f"h{i}"] = data
            prim, acting_of = await _placements(cluster, io, payloads)
            counts = {o: 0 for o in cluster.osds}
            for p in prim.values():
                counts[p] += 1
            slow = min(sorted(counts), key=lambda o: counts[o])
            targets = [o for o in payloads
                       if prim[o] != slow and slow in acting_of[o]] \
                or [o for o in payloads if prim[o] != slow]
            cluster.osds[slow].msgr.inject_internal_delays = 0.08
            # several passes: primaries learn the slow peer's EWMA,
            # hedged first-k reads stay bit-exact throughout
            for _round in range(4):
                for oid in targets:
                    assert await io.read(oid) == payloads[oid]
            evidence = sum(
                osd.hedge.counters["early_completions"]
                + osd.hedge.counters["hedges_fired"]
                for osd in cluster.osds.values())
            assert evidence > 0, "no hedging activity recorded"
            # the corrected learning semantics: fast peers earn their
            # way BELOW the prior via completed RTTs, while the
            # straggler — overtaken and cancelled on every read — is
            # never taught the winners' latency (censored samples
            # move it up only), so it can never out-rank a learned
            # fast peer
            fast_learned = False
            for osd in cluster.osds.values():
                st = osd.hedge.peers.get(slow)
                if st is not None:
                    assert st.ewma_now() >= osd.hedge.prior_s * 0.99
                for o, p in osd.hedge.peers.items():
                    if o != slow and p.samples > 0 and \
                            p.ewma_now() < osd.hedge.prior_s:
                        fast_learned = True
            assert fast_learned
            # observability surfaces
            primary = prim[targets[0]]
            rc, st = await cluster.client.osd_command(
                primary, {"prefix": "hedge_status"})
            assert rc == 0 and st["enabled"]
            assert "counters" in st and "peers" in st
            rc, perf = await cluster.client.osd_command(
                primary, {"prefix": "perf dump"})
            assert rc == 0 and "hedge" in perf
            for key in ("hedges_fired", "hedge_wins",
                        "cancelled_subreads", "peers"):
                assert key in perf["hedge"]
            # drain: no hedge task outlives its gather
            await asyncio.sleep(0.2)
            leaked = [t for t in asyncio.all_tasks()
                      if t.get_name().startswith("hedge:")
                      and not t.done()]
            assert not leaked
        finally:
            await cluster.stop()

    run(main())


def test_hedge_kill_switch_parity():
    """CEPH_TPU_HEDGE=0 restores the all-shard gather: reads remain
    byte-identical and no hedged gather ever runs."""
    os.environ["CEPH_TPU_HEDGE"] = "0"

    async def main():
        cluster = Cluster(num_osds=5, osds_per_host=1)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "kp", EC_PROFILE, pg_num=4)
            io = cluster.client.open_ioctx("kp")
            data = np.random.default_rng(77).integers(
                0, 256, 50_000, dtype=np.uint8).tobytes()
            await io.write_full("obj", data)
            assert await io.read("obj") == data
            for osd in cluster.osds.values():
                assert not osd.hedge.enabled
                assert osd.hedge.counters["hedged_gathers"] == 0
        finally:
            await cluster.stop()

    try:
        run(main())
    finally:
        os.environ.pop("CEPH_TPU_HEDGE", None)

"""CLI tool tests: rados, objectstore-tool, dencoder.

Mirrors the reference's qa workunit usage of the admin CLIs
(qa/workunits/rados/test_rados_tool.sh shape): drive real clusters and
stores through the command surfaces, parse the outputs.
"""

import asyncio
import json
import subprocess
import sys

import pytest

from cluster_helpers import Cluster

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.tpustore import TPUStore
from ceph_tpu.tools import dencoder, objectstore_tool


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


def test_rados_cli_end_to_end(tmp_path):
    """put/get/ls/stat/xattr/omap/tell/status through the CLI binary
    against a live cluster."""
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            payload = b"cli payload " * 500
            src = tmp_path / "in.bin"
            src.write_bytes(payload)
            dst = tmp_path / "out.bin"
            mon = cluster.mon.addr

            async def cli(*args, input_=None):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "ceph_tpu.tools.rados",
                    "-m", mon, *args,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    env={"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
                         "PATH": "/usr/bin:/bin:/usr/local/bin"})
                out, err = await proc.communicate(input_)
                return proc.returncode, out, err

            rc, out, err = await cli("mkpool", "data", "--size", "2",
                                     "--pg-num", "8")
            assert rc == 0, err
            rc, out, _ = await cli("lspools")
            assert b"data" in out
            rc, _, err = await cli("-p", "data", "put", "obj",
                                   str(src))
            assert rc == 0, err
            rc, _, err = await cli("-p", "data", "get", "obj",
                                   str(dst))
            assert rc == 0 and dst.read_bytes() == payload
            rc, out, _ = await cli("-p", "data", "ls")
            assert out.decode().split() == ["obj"]
            rc, out, _ = await cli("-p", "data", "stat", "obj")
            assert json.loads(out)["size"] == len(payload)
            rc, _, _ = await cli("-p", "data", "setxattr", "obj",
                                 "k", "v")
            rc, out, _ = await cli("-p", "data", "getxattr", "obj",
                                   "k")
            assert out == b"v"
            rc, _, _ = await cli("-p", "data", "setomapval", "obj",
                                 "ok", "ov")
            rc, out, _ = await cli("-p", "data", "listomapvals",
                                   "obj")
            assert b"ok: ov" in out
            rc, out, _ = await cli("status")
            assert json.loads(out)["num_up_osds"] == 3
            rc, out, _ = await cli("tell", "0", "perf", "dump")
            assert rc == 0 and "subread_bytes" in json.loads(out)
            rc, _, _ = await cli("-p", "data", "rm", "obj")
            rc, out, _ = await cli("-p", "data", "ls")
            assert out.strip() == b""
        finally:
            await cluster.stop()

    run(main())


def test_objectstore_tool_offline_surgery(tmp_path, capsys):
    store_path = str(tmp_path / "osd.0")
    store = TPUStore(store_path)
    store.mkfs()
    store.mount()
    t = Transaction()
    t.create_collection("1.0_head")
    t.touch("1.0_head", ObjectId("obj"))
    t.write("1.0_head", ObjectId("obj"), 0, len(b"stored bytes"),
            b"stored bytes")
    t.setattr("1.0_head", ObjectId("obj"), "_", b"oi")
    t.omap_setkeys("1.0_head", ObjectId("obj"), {"k": b"v"})
    store.queue_transaction(t)
    store.umount()

    def tool(*args):
        rc = objectstore_tool.main(["--data-path", store_path, *args])
        return rc, capsys.readouterr().out

    rc, out = tool("list-pgs")
    assert rc == 0 and "1.0_head" in out
    rc, out = tool("list")
    assert ["1.0_head", "obj"] in [json.loads(line)
                                   for line in out.splitlines()]
    rc, out = tool("info", "--cid", "1.0_head", "--obj", "obj")
    info = json.loads(out)
    assert info["size"] == len(b"stored bytes")
    assert info["attrs"]["_"] == "oi"
    rc, out = tool("dump-omap", "--cid", "1.0_head", "--obj", "obj")
    assert json.loads(out) == {"k": "v"}
    rc, out = tool("fsck")
    assert rc == 0 and json.loads(out)["errors"] == []
    rc, _ = tool("remove", "--cid", "1.0_head", "--obj", "obj")
    assert rc == 0
    rc, out = tool("list")
    assert "obj" not in out


def test_dencoder_round_trips(tmp_path, capsys):
    from ceph_tpu.osd.osdmap import OSDMap
    from ceph_tpu.msg.messages import MOSDOp, OSDOp
    from ceph_tpu.osd.osdmap import PgId

    m = OSDMap.build_simple(4, osds_per_host=2)
    map_file = tmp_path / "map.bin"
    map_file.write_bytes(m.encode())
    rc = dencoder.main(["type", "OSDMap", "import", str(map_file),
                        "decode", "dump_json"])
    out = capsys.readouterr().out
    assert rc == 0
    dumped = json.loads(out)
    assert dumped["max_osd"] == 4

    msg = MOSDOp(7, "client.x", PgId(1, 3), "obj",
                 [OSDOp("write_full", data=b"abc")], 42)
    frame = msg.TAG.to_bytes(2, "little") + msg.encode()
    msg_file = tmp_path / "msg.bin"
    msg_file.write_bytes(frame)
    rc = dencoder.main(["message", "import", str(msg_file), "decode"])
    out = capsys.readouterr().out
    assert rc == 0
    dumped = json.loads(out)
    assert dumped["type"] == "MOSDOp"
    assert dumped["fields"]["oid"] == "obj"

    rc = dencoder.main(["list_types"])
    out = capsys.readouterr().out
    assert "OSDMap" in out and "MOSDOp" in out


def test_rbd_cli_end_to_end(tmp_path):
    """create/ls/info/snap/clone/flatten/export/import/mirror through
    the rbd CLI binary against a live cluster (src/tools/rbd role)."""
    async def main():
        cluster = Cluster(num_osds=2)
        await cluster.start()
        try:
            mon = cluster.mon.addr
            rc0, _, err = await _rbd_cli(mon, "ls")
            # pool missing yet: make pools via the rados CLI first
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "ceph_tpu.tools.rados",
                "-m", mon, "mkpool", "rbd", "--size", "2",
                "--pg-num", "4",
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=_CLI_ENV)
            await proc.communicate()
            assert proc.returncode == 0
            proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "ceph_tpu.tools.rados",
                "-m", mon, "mkpool", "backup", "--size", "2",
                "--pg-num", "4",
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                env=_CLI_ENV)
            await proc.communicate()
            assert proc.returncode == 0

            rc, out, err = await _rbd_cli(
                mon, "create", "disk", "--size", "256K",
                "--order", "14", "--journaling")
            assert rc == 0, err
            rc, out, _ = await _rbd_cli(mon, "ls")
            assert b"disk" in out
            rc, out, err = await _rbd_cli(mon, "info", "disk")
            assert rc == 0, err
            doc = json.loads(out)
            assert doc["size"] == 256 << 10
            assert "journaling" in doc["features"]

            # write through the API, export through the CLI
            from ceph_tpu.rbd import RBD

            ioctx = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            img = await rbd.open(ioctx, "disk")
            await img.write(0, b"cli export me")
            await img.close()
            out_path = tmp_path / "disk.bin"
            rc, _, err = await _rbd_cli(mon, "export", "disk",
                                        str(out_path))
            assert rc == 0, err
            blob = out_path.read_bytes()
            assert blob[:13] == b"cli export me"
            assert len(blob) == 256 << 10

            # snapshot + protect + clone + flatten
            rc, _, err = await _rbd_cli(mon, "snap", "create",
                                        "disk@s1")
            assert rc == 0, err
            rc, _, err = await _rbd_cli(mon, "snap", "protect",
                                        "disk@s1")
            assert rc == 0, err
            rc, _, err = await _rbd_cli(mon, "clone", "disk@s1",
                                        "child")
            assert rc == 0, err
            rc, out, err = await _rbd_cli(mon, "info", "child")
            assert rc == 0, err
            assert "@s1" in json.loads(out).get("parent", "")
            rc, _, err = await _rbd_cli(mon, "flatten", "child")
            assert rc == 0, err
            rc, out, _ = await _rbd_cli(mon, "info", "child")
            assert "parent" not in json.loads(out)

            # import round-trips
            rc, _, err = await _rbd_cli(mon, "import", str(out_path),
                                        "disk2", "--order", "14")
            assert rc == 0, err
            rc, out, _ = await _rbd_cli(mon, "info", "disk2")
            assert json.loads(out)["size"] == 256 << 10

            # mirror bootstrap+replay to a second pool
            rc, out, err = await _rbd_cli(mon, "mirror", "disk",
                                          "--dst-pool", "backup")
            assert rc == 0, err
            assert json.loads(out)["bootstrapped"] is True
            bio = cluster.client.open_ioctx("backup")
            mirrored = await rbd.open(bio, "disk")
            assert await mirrored.read(0, 13) == b"cli export me"
            await mirrored.close()

            # deep-cp with snapshot history to the backup pool
            rc, out, err = await _rbd_cli(
                mon, "deep-cp", "disk", "deep", "--dest-pool",
                "backup")
            assert rc == 0, err
            deep = await rbd.open(bio, "deep")
            assert await deep.read(0, 13) == b"cli export me"
            assert [s["name"] for s in await deep.snap_list()] \
                == ["s1"]
            await deep.close()

            # migration prepare/execute/commit through the CLI
            rc, out, err = await _rbd_cli(
                mon, "migration", "prepare", "disk2", "mig",
                "--dest-pool", "backup")
            assert rc == 0, err
            rc, out, err = await _rbd_cli(
                mon, "migration", "execute", "mig",
                "--dest-pool", "backup")
            assert rc == 0, err
            rc, out, err = await _rbd_cli(
                mon, "migration", "commit", "mig",
                "--dest-pool", "backup")
            assert rc == 0, err
            rc, out, _ = await _rbd_cli(mon, "ls")
            assert b"disk2" not in out
            mig = await rbd.open(bio, "mig")
            assert await mig.read(0, 13) == b"cli export me"
            await mig.close()

            # rbd bench prints sane numbers
            rc, out, err = await _rbd_cli(
                mon, "bench", "disk", "--io-type", "readwrite",
                "--io-size", "4K", "--io-total", "64K")
            assert rc == 0, err
            doc = json.loads(out)
            assert doc["ops"] == 16
            assert doc["reads"] + doc["writes"] == 16
            assert doc["ops_per_sec"] > 0
        finally:
            await cluster.stop()

    run(main())


def test_cephfs_cli_end_to_end(tmp_path):
    """ls/mkdir/put/get/mv/snap/subvolume through the cephfs CLI
    against a live cluster (cephfs-shell + fs subvolume roles)."""
    async def main():
        from ceph_tpu.mds import MDSDaemon

        cluster = Cluster(num_osds=2)
        await cluster.start()
        mds = None
        try:
            mon = cluster.mon.addr
            await cluster.client.create_replicated_pool(
                "cephfs.meta", size=2, pg_num=4)
            await cluster.client.create_replicated_pool(
                "cephfs.data", size=2, pg_num=4)
            mds = MDSDaemon(mon, "cephfs.meta", "cephfs.data",
                            lock_interval=0.3)
            await mds.start()

            async def cli(*args, input_=None):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "ceph_tpu.tools.cephfs",
                    "-m", mon, *args,
                    stdin=subprocess.PIPE if input_ else None,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    env=_CLI_ENV)
                out, err = await proc.communicate(input_)
                return proc.returncode, out, err

            rc, _, err = await cli("mkdir", "-p", "/a/b")
            assert rc == 0, err
            src = tmp_path / "in.bin"
            src.write_bytes(b"cli file transfer")
            rc, _, err = await cli("put", str(src), "/a/b/f")
            assert rc == 0, err
            rc, out, err = await cli("cat", "/a/b/f")
            assert rc == 0 and out == b"cli file transfer", err
            rc, out, _ = await cli("ls", "/a/b")
            assert b"f" in out
            rc, _, err = await cli("mv", "/a/b/f", "/a/g")
            assert rc == 0, err
            # snapshots through the CLI
            rc, out, err = await cli("snap", "create", "/a", "s1")
            assert rc == 0, err
            rc, _, err = await cli("rm", "/a/g")
            assert rc == 0, err
            rc, out, _ = await cli("cat", "/a/.snap/s1/g")
            assert out == b"cli file transfer"
            rc, out, _ = await cli("snap", "ls", "/a")
            assert b"s1" in out
            rc, _, err = await cli("snap", "rm", "/a", "s1")
            assert rc == 0, err
            # subvolumes
            rc, out, err = await cli("subvolume", "create", "pvc",
                                     "--group", "csi", "--size",
                                     "1048576")
            assert rc == 0, err
            assert json.loads(out)["path"] == "/volumes/csi/pvc"
            rc, out, _ = await cli("subvolume", "info", "pvc",
                                   "--group", "csi")
            assert json.loads(out)["bytes_quota"] == 1048576
            rc, _, err = await cli("subvolume", "rm", "pvc",
                                   "--group", "csi")
            assert rc == 0, err
        finally:
            if mds is not None:
                await mds.stop()
            await cluster.stop()

    run(main())


_CLI_ENV = {"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
            "PATH": "/usr/bin:/bin:/usr/local/bin"}


async def _rbd_cli(mon, *args, input_=None):
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "ceph_tpu.tools.rbd", "-m", mon,
        "-p", "rbd", *args,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        env=_CLI_ENV)
    out, err = await proc.communicate(input_)
    return proc.returncode, out, err

"""CLI tool tests: rados, objectstore-tool, dencoder.

Mirrors the reference's qa workunit usage of the admin CLIs
(qa/workunits/rados/test_rados_tool.sh shape): drive real clusters and
stores through the command surfaces, parse the outputs.
"""

import asyncio
import json
import subprocess
import sys

import pytest

from cluster_helpers import Cluster

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.tpustore import TPUStore
from ceph_tpu.tools import dencoder, objectstore_tool


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


def test_rados_cli_end_to_end(tmp_path):
    """put/get/ls/stat/xattr/omap/tell/status through the CLI binary
    against a live cluster."""
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            payload = b"cli payload " * 500
            src = tmp_path / "in.bin"
            src.write_bytes(payload)
            dst = tmp_path / "out.bin"
            mon = cluster.mon.addr

            async def cli(*args, input_=None):
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "ceph_tpu.tools.rados",
                    "-m", mon, *args,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    env={"PYTHONPATH": ".", "JAX_PLATFORMS": "cpu",
                         "PATH": "/usr/bin:/bin:/usr/local/bin"})
                out, err = await proc.communicate(input_)
                return proc.returncode, out, err

            rc, out, err = await cli("mkpool", "data", "--size", "2",
                                     "--pg-num", "8")
            assert rc == 0, err
            rc, out, _ = await cli("lspools")
            assert b"data" in out
            rc, _, err = await cli("-p", "data", "put", "obj",
                                   str(src))
            assert rc == 0, err
            rc, _, err = await cli("-p", "data", "get", "obj",
                                   str(dst))
            assert rc == 0 and dst.read_bytes() == payload
            rc, out, _ = await cli("-p", "data", "ls")
            assert out.decode().split() == ["obj"]
            rc, out, _ = await cli("-p", "data", "stat", "obj")
            assert json.loads(out)["size"] == len(payload)
            rc, _, _ = await cli("-p", "data", "setxattr", "obj",
                                 "k", "v")
            rc, out, _ = await cli("-p", "data", "getxattr", "obj",
                                   "k")
            assert out == b"v"
            rc, _, _ = await cli("-p", "data", "setomapval", "obj",
                                 "ok", "ov")
            rc, out, _ = await cli("-p", "data", "listomapvals",
                                   "obj")
            assert b"ok: ov" in out
            rc, out, _ = await cli("status")
            assert json.loads(out)["num_up_osds"] == 3
            rc, out, _ = await cli("tell", "0", "perf", "dump")
            assert rc == 0 and "subread_bytes" in json.loads(out)
            rc, _, _ = await cli("-p", "data", "rm", "obj")
            rc, out, _ = await cli("-p", "data", "ls")
            assert out.strip() == b""
        finally:
            await cluster.stop()

    run(main())


def test_objectstore_tool_offline_surgery(tmp_path, capsys):
    store_path = str(tmp_path / "osd.0")
    store = TPUStore(store_path)
    store.mkfs()
    store.mount()
    t = Transaction()
    t.create_collection("1.0_head")
    t.touch("1.0_head", ObjectId("obj"))
    t.write("1.0_head", ObjectId("obj"), 0, len(b"stored bytes"),
            b"stored bytes")
    t.setattr("1.0_head", ObjectId("obj"), "_", b"oi")
    t.omap_setkeys("1.0_head", ObjectId("obj"), {"k": b"v"})
    store.queue_transaction(t)
    store.umount()

    def tool(*args):
        rc = objectstore_tool.main(["--data-path", store_path, *args])
        return rc, capsys.readouterr().out

    rc, out = tool("list-pgs")
    assert rc == 0 and "1.0_head" in out
    rc, out = tool("list")
    assert ["1.0_head", "obj"] in [json.loads(line)
                                   for line in out.splitlines()]
    rc, out = tool("info", "--cid", "1.0_head", "--obj", "obj")
    info = json.loads(out)
    assert info["size"] == len(b"stored bytes")
    assert info["attrs"]["_"] == "oi"
    rc, out = tool("dump-omap", "--cid", "1.0_head", "--obj", "obj")
    assert json.loads(out) == {"k": "v"}
    rc, out = tool("fsck")
    assert rc == 0 and json.loads(out)["errors"] == []
    rc, _ = tool("remove", "--cid", "1.0_head", "--obj", "obj")
    assert rc == 0
    rc, out = tool("list")
    assert "obj" not in out


def test_dencoder_round_trips(tmp_path, capsys):
    from ceph_tpu.osd.osdmap import OSDMap
    from ceph_tpu.msg.messages import MOSDOp, OSDOp
    from ceph_tpu.osd.osdmap import PgId

    m = OSDMap.build_simple(4, osds_per_host=2)
    map_file = tmp_path / "map.bin"
    map_file.write_bytes(m.encode())
    rc = dencoder.main(["type", "OSDMap", "import", str(map_file),
                        "decode", "dump_json"])
    out = capsys.readouterr().out
    assert rc == 0
    dumped = json.loads(out)
    assert dumped["max_osd"] == 4

    msg = MOSDOp(7, "client.x", PgId(1, 3), "obj",
                 [OSDOp("write_full", data=b"abc")], 42)
    frame = msg.TAG.to_bytes(2, "little") + msg.encode()
    msg_file = tmp_path / "msg.bin"
    msg_file.write_bytes(frame)
    rc = dencoder.main(["message", "import", str(msg_file), "decode"])
    out = capsys.readouterr().out
    assert rc == 0
    dumped = json.loads(out)
    assert dumped["type"] == "MOSDOp"
    assert dumped["fields"]["oid"] == "obj"

    rc = dencoder.main(["list_types"])
    out = capsys.readouterr().out
    assert "OSDMap" in out and "MOSDOp" in out

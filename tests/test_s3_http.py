"""S3 HTTP frontend tier: a spec-level sigv4 client (raw HTTP over a
socket, signature math from the AWS SigV4 spec) drives the gateway the
way a stock S3 client would — bucket CRUD, object round-trips with MD5
ETag verification, multipart, auth rejection.

Reference parity: the rgw_asio_frontend + rgw_auth_s3 + rgw_rest_s3
surface (/root/reference/src/rgw/)."""

import asyncio
import hashlib
import urllib.parse
import xml.etree.ElementTree as ET

import numpy as np
import pytest

from cluster_helpers import Cluster

from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.s3_frontend import S3Frontend, sign_request

ACCESS, SECRET = "AKIDEXAMPLE", "s3cr3t-key-for-tests"


class MiniS3:
    """Raw-socket S3 client: HTTP/1.1 + sigv4 from the spec."""

    def __init__(self, addr: str, access: str = ACCESS,
                 secret: str = SECRET):
        self.host, port = addr.rsplit(":", 1)
        self.port = int(port)
        self.access, self.secret = access, secret
        self._r = self._w = None

    async def _connect(self):
        if self._w is None or self._w.is_closing():
            self._r, self._w = await asyncio.open_connection(
                self.host, self.port, limit=8 << 20)

    async def request(self, method, path, query=None, body=b"",
                      sign=True):
        await self._connect()
        query = query or {}
        headers = {"Host": f"{self.host}:{self.port}"}
        if sign:
            headers = sign_request(method, path, query, headers, body,
                                   self.access, self.secret)
        qs = urllib.parse.urlencode(query)
        target = path + ("?" + qs if qs else "")
        req = [f"{method} {target} HTTP/1.1\r\n"]
        headers["Content-Length"] = str(len(body))
        for k, v in headers.items():
            req.append(f"{k}: {v}\r\n")
        req.append("\r\n")
        self._w.write("".join(req).encode() + body)
        await self._w.drain()
        status_line = await self._r.readline()
        status = int(status_line.split()[1])
        rhdrs = {}
        while True:
            line = await self._r.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            rhdrs[k.strip().lower()] = v.strip()
        length = int(rhdrs.get("content-length", "0"))
        rbody = await self._r.readexactly(length) if length and \
            method != "HEAD" else b""
        return status, rhdrs, rbody

    async def close(self):
        if self._w is not None:
            self._w.close()
            self._w = None


async def _stack(cluster):
    await cluster.client.create_replicated_pool(
        "rgw.meta", size=2, pg_num=4)
    await cluster.client.create_ec_pool(
        "rgw.data", {"plugin": "ec_jax", "technique": "reed_sol_van",
                     "k": "2", "m": "1", "crush-failure-domain": "osd",
                     "tpu": "false"}, pg_num=4)
    rgw = RGWLite(cluster.client, "rgw.data", "rgw.meta")
    fe = S3Frontend(rgw, {ACCESS: SECRET})
    addr = await fe.start()
    return fe, addr


def test_s3_http_object_lifecycle():
    async def run():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            s3 = MiniS3(addr)
            # bucket create + list buckets
            st, _, _ = await s3.request("PUT", "/photos")
            assert st == 200
            st, _, xml_body = await s3.request("GET", "/")
            assert st == 200 and b"photos" in xml_body
            # PUT: ETag is the true MD5
            data = np.random.default_rng(3).integers(
                0, 256, 300_000, dtype=np.uint8).tobytes()
            st, h, _ = await s3.request("PUT", "/photos/cat.jpg",
                                        body=data)
            assert st == 200
            assert h["etag"].strip('"') == \
                hashlib.md5(data).hexdigest()
            # GET round-trips the bytes + ETag
            st, h, got = await s3.request("GET", "/photos/cat.jpg")
            assert st == 200 and got == data
            assert h["etag"].strip('"') == \
                hashlib.md5(data).hexdigest()
            # HEAD
            st, h, empty = await s3.request("HEAD", "/photos/cat.jpg")
            assert st == 200 and empty == b""
            # list with prefix
            st, _, xml_body = await s3.request(
                "GET", "/photos", query={"prefix": "cat"})
            assert b"cat.jpg" in xml_body
            # DELETE + 404 after
            st, _, _ = await s3.request("DELETE", "/photos/cat.jpg")
            assert st == 204
            st, _, _ = await s3.request("GET", "/photos/cat.jpg")
            assert st == 404
            # empty-bucket delete
            st, _, _ = await s3.request("DELETE", "/photos")
            assert st == 204
            await s3.close()
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))


def test_s3_http_multipart_round_trip():
    async def run():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            s3 = MiniS3(addr)
            await s3.request("PUT", "/vids")
            payload = np.random.default_rng(9).integers(
                0, 256, 12 << 20, dtype=np.uint8).tobytes()
            psize = 4 << 20
            st, _, body = await s3.request(
                "POST", "/vids/movie.bin", query={"uploads": ""})
            assert st == 200
            upload_id = ET.fromstring(body).findtext("UploadId")
            etags = []
            for num in range(1, 4):
                chunk = payload[(num - 1) * psize:num * psize]
                st, h, _ = await s3.request(
                    "PUT", "/vids/movie.bin",
                    query={"partNumber": str(num),
                           "uploadId": upload_id},
                    body=chunk)
                assert st == 200
                assert h["etag"].strip('"') == \
                    hashlib.md5(chunk).hexdigest()
                etags.append(h["etag"].strip('"'))
            comp = ET.Element("CompleteMultipartUpload")
            for num, etag in enumerate(etags, 1):
                p = ET.SubElement(comp, "Part")
                ET.SubElement(p, "PartNumber").text = str(num)
                ET.SubElement(p, "ETag").text = etag
            st, _, body = await s3.request(
                "POST", "/vids/movie.bin",
                query={"uploadId": upload_id},
                body=ET.tostring(comp))
            assert st == 200
            final_etag = ET.fromstring(body).findtext(
                "ETag").strip('"')
            want = hashlib.md5(b"".join(
                bytes.fromhex(e) for e in etags)).hexdigest() + "-3"
            assert final_etag == want
            st, h, got = await s3.request("GET", "/vids/movie.bin")
            assert st == 200 and got == payload
            assert h["etag"].strip('"') == want
            await s3.close()
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 180))


def test_s3_http_auth_rejection():
    async def run():
        cluster = Cluster(num_osds=3, osds_per_host=1)
        await cluster.start()
        fe = None
        try:
            fe, addr = await _stack(cluster)
            # no auth header at all
            anon = MiniS3(addr)
            st, _, body = await anon.request("GET", "/", sign=False)
            assert st == 403 and b"AccessDenied" in body
            await anon.close()
            # wrong secret: SignatureDoesNotMatch
            bad = MiniS3(addr, secret="wrong-secret")
            st, _, body = await bad.request("GET", "/")
            assert st == 403 and b"SignatureDoesNotMatch" in body
            await bad.close()
            # unknown access key
            ghost = MiniS3(addr, access="AKIDGHOST")
            st, _, body = await ghost.request("GET", "/")
            assert st == 403
            await ghost.close()
            # tampered body under a signed payload hash
            s3 = MiniS3(addr)
            await s3.request("PUT", "/b1")
            headers = sign_request(
                "PUT", "/b1/obj", {}, {"Host": addr}, b"real body",
                ACCESS, SECRET)
            req = ["PUT /b1/obj HTTP/1.1\r\n"]
            headers["Content-Length"] = str(len(b"fake body"))
            for k, v in headers.items():
                req.append(f"{k}: {v}\r\n")
            req.append("\r\n")
            await s3._connect()
            s3._w.write("".join(req).encode() + b"fake body")
            await s3._w.drain()
            status = int((await s3._r.readline()).split()[1])
            assert status == 403
            await s3.close()
        finally:
            if fe is not None:
                await fe.stop()
            await cluster.stop()

    asyncio.run(asyncio.wait_for(run(), 120))

"""CLAY plugin tests, mirroring the reference's TestErasureCodeClay.cc:
full-decode sweeps, sub-chunked repair with reduced bandwidth, shortened
(nu > 0) geometries, parameter validation."""

import itertools

import numpy as np
import pytest

from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ec.registry import create_erasure_code


def make(k=4, m=2, d=None, **extra):
    profile = {"plugin": "clay", "k": str(k), "m": str(m), **extra}
    if d is not None:
        profile["d"] = str(d)
    return create_erasure_code(profile)


def payload(clay, stripes=4, seed=0):
    size = clay.get_chunk_size(1) * clay.k * stripes
    return np.random.default_rng(seed).integers(
        0, 256, size, dtype=np.uint8).tobytes()


def test_geometry_default():
    clay = make(4, 2)           # d = k+m-1 = 5, q = 2, t = 3
    assert clay.d == 5
    assert clay.q == 2 and clay.t == 3 and clay.nu == 0
    assert clay.get_sub_chunk_count() == 8
    assert clay.get_chunk_count() == 6


def test_geometry_shortened():
    clay = make(4, 3)           # d = 6, q = 3, k+m = 7 -> nu = 2
    assert clay.q == 3 and clay.nu == 2 and clay.t == 3
    assert clay.get_sub_chunk_count() == 27


def test_validation():
    with pytest.raises(ErasureCodeError):
        make(4, 2, d=3)         # d < k
    with pytest.raises(ErasureCodeError):
        make(4, 2, d=6)         # d > k+m-1
    with pytest.raises(ErasureCodeError):
        make(4, 2, scalar_mds="bogus")


@pytest.mark.parametrize("km", [(4, 2), (4, 3), (6, 3)])
def test_round_trip_and_full_decode(km):
    k, m = km
    clay = make(k, m)
    n = k + m
    data = payload(clay, stripes=2, seed=k)
    full = clay.encode(range(n), data)
    assert len(full) == n
    assert clay.decode_concat(full)[:len(data)] == data
    # all single and double erasures (up to m)
    for r in range(1, min(m, 2) + 1):
        for erased in itertools.combinations(range(n), r):
            avail = {i: c for i, c in full.items() if i not in erased}
            out = clay.decode(set(erased), avail)
            for i in erased:
                assert out[i] == full[i], (km, erased)


def test_triple_erasure_m3():
    clay = make(6, 3)
    data = payload(clay, seed=7)
    full = clay.encode(range(9), data)
    for erased in ([0, 4, 8], [1, 2, 3], [6, 7, 8]):
        avail = {i: c for i, c in full.items() if i not in erased}
        out = clay.decode(set(erased), avail)
        for i in erased:
            assert out[i] == full[i]


def test_repair_is_detected():
    clay = make(4, 2)
    # single lost chunk with all others up -> repair mode
    assert clay.is_repair({1}, set(range(6)) - {1})
    # two lost -> not repair
    assert not clay.is_repair({1, 2}, set(range(6)) - {1, 2})
    # wanted chunk available -> not repair
    assert not clay.is_repair({1}, set(range(6)))


def test_minimum_to_repair_subchunks():
    clay = make(4, 2)           # d=5, sub=8, repair reads sub/q = 4
    minimum = clay.minimum_to_decode({2}, set(range(6)) - {2})
    assert len(minimum) == clay.d
    assert 2 not in minimum
    for node, ranges in minimum.items():
        count = sum(c for _off, c in ranges)
        assert count == clay.get_sub_chunk_count() // clay.q


@pytest.mark.parametrize("km_d", [(4, 2, 5), (6, 3, 8), (4, 3, 6), (8, 4, 11)])
def test_repair_each_node_bit_exact(km_d):
    """The MSR contract: every single chunk is repairable from d helpers
    reading only their repair sub-chunks, bit-exactly."""
    k, m, d = km_d
    clay = make(k, m, d=d)
    n = k + m
    data = payload(clay, stripes=1, seed=d)
    full = clay.encode(range(n), data)
    chunk_size = len(full[0])
    sub = clay.get_sub_chunk_count()
    sc = chunk_size // sub
    for lost in range(n):
        minimum = clay.minimum_to_decode({lost}, set(range(n)) - {lost})
        assert len(minimum) == d
        # helpers send only the repair sub-chunk ranges, concatenated
        partial = {}
        for node, ranges in minimum.items():
            buf = b"".join(full[node][off * sc:(off + c) * sc]
                           for off, c in ranges)
            partial[node] = buf
        assert len(next(iter(partial.values()))) < chunk_size  # bandwidth win
        out = clay.decode({lost}, partial, chunk_size=chunk_size)
        assert out[lost] == full[lost], f"lost={lost}"


def test_repair_bandwidth_ratio():
    """Repair reads d/(d-k+1) fraction; for (8,4,11) that's 11/4 subchunks
    of 64 vs 8 full chunks -> strictly less than k*chunk."""
    clay = make(8, 4, d=11)
    sub = clay.get_sub_chunk_count()
    per_helper = sub // clay.q
    total_read = clay.d * per_helper
    naive_read = clay.k * sub
    assert total_read < naive_read
    assert total_read / naive_read < 0.5


def test_fallback_full_decode_when_not_repair():
    clay = make(4, 2)
    data = payload(clay, seed=3)
    full = clay.encode(range(6), data)
    # two erasures: normal full decode path through minimum_to_decode
    minimum = clay.minimum_to_decode({0, 1}, set(range(6)) - {0, 1})
    for node, ranges in minimum.items():
        assert ranges == [(0, clay.get_sub_chunk_count())]
    avail = {i: full[i] for i in minimum}
    out = clay.decode({0, 1}, avail)
    assert out[0] == full[0] and out[1] == full[1]


def test_chunk_size_divisible_by_subchunks():
    clay = make(4, 2)
    for size in (1, 1000, 12345, 1 << 20):
        cs = clay.get_chunk_size(size)
        assert cs % clay.get_sub_chunk_count() == 0
        assert cs * clay.k >= size


def test_too_many_erasures_raises():
    clay = make(4, 2)
    data = payload(clay, seed=1)
    full = clay.encode(range(6), data)
    avail = {i: full[i] for i in (0, 1, 2)}  # 3 erasures > m=2
    with pytest.raises(ErasureCodeError):
        clay.decode({3, 4, 5}, avail)

"""End-to-end single-host slice tests (SURVEY.md §7.6): put -> stripe ->
TPU encode -> CRUSH-placed shards + hinfo; get with erasures -> TPU
decode; deep scrub and repair.  Mirrors the shape of
qa/standalone/erasure-code/test-erasure-code.sh and test-erasure-eio.sh."""

import json

import numpy as np
import pytest

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.rados.embedded import (
    HINFO_ATTR,
    LocalCluster,
    shard_collection,
)

EC_PROFILE = {"plugin": "jerasure", "technique": "reed_sol_van",
              "k": "4", "m": "2", "crush-failure-domain": "osd"}


@pytest.fixture
def cluster():
    c = LocalCluster(num_osds=8, osds_per_host=2)
    c.create_erasure_pool("ecpool", EC_PROFILE, pg_num=16)
    c.create_replicated_pool("repl", size=3, pg_num=16)
    yield c
    c.shutdown()


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_ec_put_get_round_trip(cluster):
    io = cluster.open_ioctx("ecpool")
    for size in (0, 1, 4095, 4096, 100_000, 1 << 20):
        data = payload(size, seed=size % 97)
        io.write_full(f"obj-{size}", data)
        assert io.read(f"obj-{size}") == data, size
        assert io.stat(f"obj-{size}")["size"] == size


def test_ec_shards_are_placed_by_crush(cluster):
    io = cluster.open_ioctx("ecpool")
    data = payload(50_000, seed=1)
    io.write_full("placed", data)
    pg = io.object_pg("placed")
    acting, primary = io.acting(pg)
    assert len(acting) == 6                     # k+m
    assert len({o for o in acting if o >= 0}) == 6
    # each shard really lives on its acting osd with an hinfo ledger
    for shard, osd in enumerate(acting):
        store = cluster.stores[osd]
        cid = shard_collection(pg, shard)
        buf = store.read(cid, ObjectId("placed"))
        assert len(buf) > 0
        hinfo = json.loads(store.getattr(cid, ObjectId("placed"),
                                         HINFO_ATTR))
        assert len(hinfo["cumulative_shard_hashes"]) == 6


def test_ec_degraded_read_with_down_osds(cluster):
    io = cluster.open_ioctx("ecpool")
    data = payload(300_000, seed=2)
    io.write_full("degraded", data)
    pg = io.object_pg("degraded")
    acting, _p = io.acting(pg)
    # kill m=2 of the shard holders: read must still reconstruct
    cluster.mark_osd_down(acting[0])
    cluster.mark_osd_down(acting[3])
    assert io.read("degraded") == data


def test_ec_too_many_failures(cluster):
    io = cluster.open_ioctx("ecpool")
    io.write_full("doomed", payload(10_000, seed=3))
    pg = io.object_pg("doomed")
    acting, _p = io.acting(pg)
    for osd in acting[:3]:                      # 3 > m=2
        cluster.mark_osd_down(osd)
    with pytest.raises(Exception):
        io.read("doomed")


def test_ec_corrupt_shard_detected_and_reconstructed(cluster):
    """EIO-injection shape of test-erasure-eio.sh: a shard corrupted on
    disk fails its hinfo crc and the read reconstructs around it."""
    io = cluster.open_ioctx("ecpool")
    data = payload(200_000, seed=4)
    io.write_full("bitrot", data)
    pg = io.object_pg("bitrot")
    acting, _p = io.acting(pg)
    victim_shard = 1
    store = cluster.stores[acting[victim_shard]]
    cid = shard_collection(pg, victim_shard)
    buf = bytearray(store.read(cid, ObjectId("bitrot")))
    buf[100] ^= 0xFF
    t = Transaction()
    t.write(cid, ObjectId("bitrot"), 0, len(buf), bytes(buf))
    store.queue_transaction(t)                  # corrupt, hinfo unchanged
    assert io.read("bitrot") == data            # reconstructed
    problems = io.deep_scrub("bitrot")
    assert any(shard == victim_shard and "crc" in why
               for shard, why in problems)


def test_ec_repair_rewrites_bad_shard(cluster):
    io = cluster.open_ioctx("ecpool")
    data = payload(150_000, seed=5)
    io.write_full("fixme", data)
    pg = io.object_pg("fixme")
    acting, _p = io.acting(pg)
    # destroy shard 2 entirely
    store = cluster.stores[acting[2]]
    t = Transaction()
    t.remove(shard_collection(pg, 2), ObjectId("fixme"))
    store.queue_transaction(t)
    assert io.deep_scrub("fixme")
    repaired = io.repair("fixme")
    assert repaired == [2]
    assert io.deep_scrub("fixme") == []
    assert io.read("fixme") == data


def test_replicated_pool(cluster):
    io = cluster.open_ioctx("repl")
    data = payload(80_000, seed=6)
    io.write_full("robj", data)
    assert io.read("robj") == data
    pg = io.object_pg("robj")
    acting, _p = io.acting(pg)
    assert len(acting) == 3
    # any single copy serves the read
    cluster.mark_osd_down(acting[0])
    assert io.read("robj") == data
    assert io.deep_scrub("robj") == []


def test_remove_and_list(cluster):
    io = cluster.open_ioctx("ecpool")
    for i in range(5):
        io.write_full(f"o{i}", payload(1000, seed=i))
    assert io.list_objects() == [f"o{i}" for i in range(5)]
    io.remove("o2")
    assert io.list_objects() == ["o0", "o1", "o3", "o4"]
    with pytest.raises(KeyError):
        io.read("o2")


def test_lrc_pool_end_to_end(cluster):
    cluster.create_erasure_pool(
        "lrcpool", {"plugin": "lrc", "k": "4", "m": "2", "l": "3",
                    "crush-failure-domain": "osd"}, pg_num=8)
    io = cluster.open_ioctx("lrcpool")
    data = payload(64_000, seed=7)
    io.write_full("lrcobj", data)
    assert io.read("lrcobj") == data
    pg = io.object_pg("lrcobj")
    acting, _p = io.acting(pg)
    assert len(acting) == 8                     # k+m+groups
    cluster.mark_osd_down(acting[1])
    assert io.read("lrcobj") == data


def test_unknown_pool_and_object(cluster):
    with pytest.raises(KeyError):
        cluster.open_ioctx("nope")
    io = cluster.open_ioctx("ecpool")
    with pytest.raises(KeyError):
        io.read("never-written")
    with pytest.raises(KeyError):
        io.stat("never-written")


def test_persistent_cluster_round_trip(tmp_path):
    """The same slice over TPUStore-backed OSDs survives remount."""
    c = LocalCluster(num_osds=6, osds_per_host=2,
                     store_path=str(tmp_path))
    c.create_erasure_pool("ecpool", EC_PROFILE, pg_num=8)
    io = c.open_ioctx("ecpool")
    data = payload(500_000, seed=8)
    io.write_full("durable", data)
    assert io.read("durable") == data
    c.shutdown()

"""HitSet oracle tier (osd/hitset.py).

The acceptance shape: device-batched bloom insert/contains matches the
host rjenkins oracle bit-exactly; the bloom false-positive rate stays
inside its configured budget; the per-PG stack rotates and decays like
the reference's hit_set_count/hit_set_period machinery; and sets
survive the persistence round-trip byte-exactly.
"""

from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.osd import hitset as hm

RNG = np.random.default_rng(23)


def _oid_hashes(prefix: str, n: int) -> np.ndarray:
    return np.array([hm.hash_oid(f"{prefix}{i}") for i in range(n)],
                    dtype=np.uint32)


# -- device vs host bit-exactness -------------------------------------------


def test_device_positions_match_host_oracle():
    """The jnp-batched bloom positions equal the numpy rjenkins path
    bit-for-bit — uint32 wraparound is exact on both lanes."""
    jax = pytest.importorskip("jax")  # noqa: F841
    hashes = _oid_hashes("obj_", 500)
    for target, fpp in ((256, 0.05), (1024, 0.01), (64, 0.2)):
        nbits, nhash = hm.bloom_geometry(target, fpp)
        host = hm.bloom_positions(hashes, nbits, nhash, xp=np)
        dev = hm.positions_for(hashes, nbits, nhash, device=True)
        assert host.dtype == dev.dtype == np.uint32
        assert np.array_equal(host, dev)


def test_device_and_host_inserts_build_identical_filters():
    jax = pytest.importorskip("jax")  # noqa: F841
    hashes = _oid_hashes("ins_", 300)
    via_dev = hm.BloomHitSet(512, 0.05)
    via_host = hm.BloomHitSet(512, 0.05)
    via_dev.insert_batch(hashes, device=True)
    via_host.insert_batch(hashes, device=False)
    assert np.array_equal(via_dev.bits, via_host.bits)
    # contains agrees on members and (arbitrary) non-members, through
    # both dispatch paths
    probe = np.concatenate([hashes[:50], _oid_hashes("other_", 200)])
    got_dev = via_dev.contains_batch(probe, device=True)
    got_host = via_host.contains_batch(probe, device=False)
    assert np.array_equal(got_dev, got_host)
    assert got_dev[:50].all()


def test_single_and_batch_paths_agree():
    hs = hm.BloomHitSet(256, 0.05)
    hashes = _oid_hashes("s_", 64)
    for h in hashes[:32]:
        hs.insert(int(h))
    batch = hm.BloomHitSet(256, 0.05, nbits=hs.nbits, nhash=hs.nhash)
    batch.insert_batch(hashes[:32], device=False)
    assert np.array_equal(hs.bits, batch.bits)
    for h in hashes[:32]:
        assert hs.contains(int(h))


# -- false-positive bound ---------------------------------------------------


def test_bloom_false_positive_rate_within_budget():
    """At the configured target size, the measured fp rate on 20k
    non-members stays within 2x the configured probability (the
    standard slack for the pointwise bound)."""
    for fpp in (0.05, 0.01):
        hs = hm.BloomHitSet(target_size=1024, fpp=fpp)
        members = _oid_hashes("m_", 1024)
        hs.insert_batch(members)
        others = _oid_hashes("x_", 20000)
        member_set = {int(h) for h in members}
        mask = np.array([int(h) not in member_set for h in others])
        rate = hs.contains_batch(others)[mask].mean()
        assert rate <= 2.0 * fpp, f"fp rate {rate} vs budget {fpp}"
        # zero false negatives, ever
        assert hs.contains_batch(members).all()


def test_explicit_hash_hitset_is_exact():
    hs = hm.ExplicitHashHitSet()
    members = _oid_hashes("e_", 500)
    hs.insert_batch(members)
    assert hs.contains_batch(members).all()
    others = _oid_hashes("not_", 500)
    member_set = {int(h) for h in members}
    mask = np.array([int(h) not in member_set for h in others])
    assert not hs.contains_batch(others)[mask].any()


# -- rotation / decay -------------------------------------------------------


def test_stack_rotation_and_decay():
    """count=3 keeps the open set + 2 archived; the third rotation
    pushes the oldest period off the stack (the decay)."""
    st = hm.HitSetStack(count=3, period=3600.0, target_size=64)
    hot, cold = hm.hash_oid("hot"), hm.hash_oid("cold")
    st.insert(hot)
    st.insert(cold)
    assert st.hit_count(hot) == 1 and st.hit_count(cold) == 1
    st.rotate()
    assert st.open_count(hot) == 0       # open set reset
    assert st.hit_count(hot) == 1        # archived membership
    st.insert(hot)
    assert st.hit_count(hot) == 2        # open + 1 archived
    st.rotate()                           # archive #2 (has hot)
    st.rotate()                           # archive #3: period-1 decays
    assert len(st.archived) == 2
    assert st.hit_count(cold) == 0, "cold should have decayed off"
    assert st.hit_count(hot) == 1, "only the hot period survives"


def test_stack_open_counts_feed_read_frequencies():
    st = hm.HitSetStack(count=4, period=3600.0)
    for _ in range(5):
        st.insert(hm.hash_oid("a"))
    st.insert(hm.hash_oid("b"))
    assert sorted(st.read_frequencies()) == [1, 5]
    # a burst within one period registers as hot (promote signal)
    assert st.hit_count(hm.hash_oid("a")) == 5


def test_stack_due_is_period_driven():
    st = hm.HitSetStack(count=2, period=0.0)
    assert not st.due()                  # period 0 = never auto-rotate
    st2 = hm.HitSetStack(count=2, period=1e-9)
    st2.opened -= 1.0
    assert st2.due()


# -- persistence round-trip -------------------------------------------------


def test_bloom_serialization_roundtrip():
    hs = hm.BloomHitSet(512, 0.02)
    hashes = _oid_hashes("ser_", 400)
    hs.insert_batch(hashes)
    back = hm.hitset_from_dict(hs.to_dict())
    assert isinstance(back, hm.BloomHitSet)
    assert (back.nbits, back.nhash, back.count) == \
        (hs.nbits, hs.nhash, hs.count)
    assert np.array_equal(back.bits, hs.bits)
    assert back.contains_batch(hashes).all()


def test_explicit_serialization_roundtrip():
    hs = hm.ExplicitHashHitSet()
    hashes = _oid_hashes("ser2_", 100)
    hs.insert_batch(hashes)
    back = hm.hitset_from_dict(hs.to_dict())
    assert isinstance(back, hm.ExplicitHashHitSet)
    assert back.hashes == hs.hashes


def test_geometry_scales_with_budget():
    """Tighter fpp or larger target -> more bits; nhash stays small."""
    b1, k1 = hm.bloom_geometry(1024, 0.05)
    b2, k2 = hm.bloom_geometry(1024, 0.01)
    b3, _k3 = hm.bloom_geometry(4096, 0.05)
    assert b2 > b1 and b3 > b1
    assert 1 <= k1 <= 32 and 1 <= k2 <= 32

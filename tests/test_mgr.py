"""MGR role tests: balancer (upmap), pg_autoscaler, prometheus, tell.

Mirrors the reference's qa checks for pybind/mgr modules: the balancer
must actually flatten the PG distribution through committed map
changes, the exporter must serve parseable exposition text, and the
`ceph tell osd.N` surface must answer admin commands over the wire.
"""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.mgr import MgrDaemon


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def _start_mgr(cluster, config=None):
    mgr = MgrDaemon(cluster.mon.addr, config=config or {})
    await mgr.start()
    return mgr


def test_osd_tell_perf_dump():
    """MOSDCommand: admin-socket command table over the wire."""
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o", b"x" * 1000)
            rc, perf = await cluster.client.osd_command(
                0, {"prefix": "perf dump"})
            assert rc == 0
            assert "encode_dispatches" in perf
            rc, pgs = await cluster.client.osd_command(
                0, {"prefix": "dump_pgs"})
            assert rc == 0 and isinstance(pgs, dict)
            rc, out = await cluster.client.osd_command(
                0, {"prefix": "nonesuch"})
            assert rc != 0
        finally:
            await cluster.stop()

    run(main())


def test_balancer_flattens_distribution():
    """The balancer's committed upmaps must reduce the per-OSD PG
    spread to within max_deviation, through real map epochs, without
    disturbing stored data."""
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=64)
            io = cluster.client.open_ioctx("p")
            payloads = {f"obj-{i}": bytes([i]) * 4096
                        for i in range(10)}
            for name, data in payloads.items():
                await io.write_full(name, data)
            mgr = await _start_mgr(cluster)
            balancer = mgr.modules["balancer"]
            before = balancer.eval_pool(io.pool_id)
            applied = await balancer.optimize()
            await mgr.client.refresh_map()
            after = balancer.eval_pool(io.pool_id)
            assert after["max_deviation"] <= balancer.max_deviation, \
                (before, after)
            # straw2 over 6 OSDs at 64 PGs is essentially never
            # perfectly flat: the run must have moved something
            assert applied > 0 or \
                before["max_deviation"] <= balancer.max_deviation
            # upmaps committed as ordinary map state
            assert cluster.mon.osdmap.pg_upmap_items or applied == 0
            # the cluster re-peers and data survives the remaps
            await cluster.wait_for_clean()
            for name, data in payloads.items():
                assert await io.read(name) == data
            await mgr.stop()
        finally:
            await cluster.stop()

    run(main())


def test_rm_pg_upmap_items():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            pool_id = cluster.client.open_ioctx("p").pool_id
            from ceph_tpu.osd.osdmap import PgId

            pg = PgId(pool_id, 0)
            acting, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
            spare = next(o for o in range(4) if o not in acting)
            rc, _ = await cluster.client.mon_command({
                "prefix": "osd pg-upmap-items",
                "pgid": f"{pool_id}.0",
                "mappings": [[acting[0], spare]]})
            assert rc == 0
            await cluster.client.refresh_map()
            now, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
            assert spare in now and acting[0] not in now
            rc, _ = await cluster.client.mon_command({
                "prefix": "osd rm-pg-upmap-items",
                "pgid": f"{pool_id}.0"})
            assert rc == 0
            back, _p = cluster.mon.osdmap.pg_to_acting_osds(pg)
            assert back == acting
        finally:
            await cluster.stop()

    run(main())


def test_autoscaler_recommends_more_pgs():
    async def main():
        cluster = Cluster(num_osds=6, osds_per_host=3)
        await cluster.start()
        try:
            # 8 PGs x2 over 6 OSDs is far below 100 PGs/OSD: the
            # autoscaler must flag it
            await cluster.client.create_replicated_pool(
                "tiny", size=2, pg_num=8)
            mgr = await _start_mgr(cluster)
            scaler = mgr.modules["pg_autoscaler"]
            rows = scaler.compute()
            assert rows, "no recommendations"
            row = next(iter(rows.values()))
            assert row["pg_num_ideal"] > row["pg_num_current"]
            assert row["would_adjust"]
            assert scaler.health_warnings()
            await mgr.stop()
        finally:
            await cluster.stop()

    run(main())


def test_prometheus_exporter_serves_metrics():
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o", b"y" * 2048)
            mgr = await _start_mgr(cluster)
            prom = mgr.modules["prometheus"]
            host, port = prom.addr.split(":")
            reader, writer = await asyncio.open_connection(
                host, int(port))
            writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(), 10.0)
            writer.close()
            text = raw.decode()
            assert text.startswith("HTTP/1.0 200")
            body = text.split("\r\n\r\n", 1)[1]
            assert "ceph_osdmap_epoch" in body
            assert 'ceph_osd_up{ceph_daemon="osd.0"} 1' in body
            assert 'ceph_pool_pg_num{pool="p"} 8' in body
            assert "ceph_pg_per_osd" in body
            assert "ceph_health_status" in body
            # per-OSD perf scraped over the tell surface
            assert "ceph_osd_encode_dispatches" in body or \
                   "ceph_osd_subread_bytes" in body
            # the hedge section flattened out of the nested perf dump
            assert "ceph_osd_hedge_hedges_fired" in body
            assert "ceph_osd_hedge_cancelled_subreads" in body
            # every non-comment line parses as `name{labels} value`
            for line in body.strip().splitlines():
                if line.startswith("#"):
                    continue
                name_part, value = line.rsplit(" ", 1)
                float(value)
                assert name_part[0].isalpha()
            await mgr.stop()
        finally:
            await cluster.stop()

    run(main())


def test_prometheus_flattens_hedge_peers():
    """The hedge section's per-peer EWMA map becomes peer-labeled
    rows (like profiles/per_plan become profile-labeled), with the
    moving estimates typed as gauges."""
    from ceph_tpu.mgr.prometheus import PrometheusModule

    lines: list = []
    seen: set = set()
    PrometheusModule._emit_perf(
        lines, seen, "ceph_osd_hedge",
        {"hedges_fired": 3, "hedge_wins": 2, "cancelled_subreads": 5,
         "peers": {"osd.1": {"ewma_ms": 2.5, "p95_ms": 4.0,
                             "samples": 7, "state_code": 0}}},
        {"ceph_daemon": "osd.0"})
    body = "\n".join(lines)
    assert 'ceph_osd_hedge_hedges_fired{ceph_daemon="osd.0"} 3' in body
    assert ('ceph_osd_hedge_peer_ewma_ms{ceph_daemon="osd.0",'
            'peer="osd.1"} 2.5') in body
    assert ('ceph_osd_hedge_peer_samples{ceph_daemon="osd.0",'
            'peer="osd.1"} 7') in body
    # moving estimates are gauges, not counters
    assert "# TYPE ceph_osd_hedge_peer_ewma_ms gauge" in body
    assert "# TYPE ceph_osd_hedge_peer_p95_ms gauge" in body
    assert "# TYPE ceph_osd_hedge_hedges_fired counter" in body


def test_dashboard_serves_status_ui():
    """The dashboard module answers the HTML page and every /api/*
    document with live cluster state (read-only mgr UI role)."""
    async def main():
        import json

        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=2, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("o", b"z" * 1024)
            mgr = await _start_mgr(cluster)
            dash = mgr.modules["dashboard"]
            host, port = dash.addr.split(":")

            async def get(path):
                reader, writer = await asyncio.open_connection(
                    host, int(port))
                writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(), 10.0)
                writer.close()
                head, body = raw.decode().split("\r\n\r\n", 1)
                return head, body

            head, body = await get("/")
            assert head.startswith("HTTP/1.0 200")
            assert "text/html" in head and "ceph_tpu" in body

            head, body = await get("/api/status")
            assert head.startswith("HTTP/1.0 200")
            doc = json.loads(body)
            assert doc["num_up_osds"] == 3
            assert doc["health"]["status"] == "HEALTH_OK"
            assert any(p["name"] == "p" and p["pg_num"] == 8
                       for p in doc["pool_table"])

            _, body = await get("/api/osds")
            osds = json.loads(body)["osds"]
            assert len(osds) == 3 and all(o["up"] for o in osds)
            assert sum(o["pgs"] for o in osds) == 16  # 8 pgs x size 2

            _, body = await get("/api/mons")
            assert json.loads(body)["num_mons"] >= 1

            _, body = await get("/api/log")
            assert isinstance(json.loads(body)["lines"], list)

            _, body = await get("/api/df")
            df = json.loads(body)
            assert df["cluster"]["total_bytes"] > 0
            assert any(p["name"] == "p" and p["bytes_used"] >= 1024
                       for p in df["pools"])

            head, _ = await get("/api/nonesuch")
            assert head.startswith("HTTP/1.0 404")
            await mgr.stop()
        finally:
            await cluster.stop()

    run(main())


def test_telemetry_report_anonymized():
    """The telemetry module compiles an anonymized cluster snapshot
    (shapes and counts, never pool/object names) and persists it for
    support-bundle pickup (telemetry module role, egress-free)."""
    async def main():
        import json as _json

        cluster = Cluster(num_osds=3)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "userdata-secret-name", size=2, pg_num=8)
            mgr = await _start_mgr(cluster)
            tel = mgr.modules["telemetry"]
            doc = await tel.compile_and_store()
            assert doc["osd"] == {"count": 3, "up": 3, "in": 3}
            assert doc["health"]["status"] == "HEALTH_OK"
            assert doc["mon"]["count"] >= 1
            assert any(p["pg_num"] == 8 for p in doc["pools"])
            # anonymization: the pool NAME never appears anywhere
            assert "userdata-secret-name" not in _json.dumps(doc)
            # persisted report readable from the cluster
            io = cluster.client.open_ioctx("userdata-secret-name")
            from ceph_tpu.mgr.telemetry import REPORT_OBJ

            raw = await io.read(REPORT_OBJ)
            assert _json.loads(raw.decode())["osd"]["count"] == 3
            await mgr.stop()
        finally:
            await cluster.stop()

    run(main())

"""Crash-consistency tier: power-cut fault injection for TPUStore and
durable OSD restarts.

Store level (os/faultstore.py, the CrashMonkey/ALICE shape): a mixed
write/overwrite/deferred/omap workload is recorded, every legal
post-crash image (prefix cuts, dropped/reordered un-synced writes,
torn partial-sector writes) is synthesized, remounted and checked —
mount succeeds, acked transactions are fully visible, journal replay
is idempotent (including a second crash DURING replay), checksums are
clean, the freelist and blob map agree.  A deliberately broken store
(fsync removed / commit demoted) must be CAUGHT by the same sweep —
the harness self-test.

Cluster level (tests/cluster_helpers.py persistent mode): kill_osd
crash-closes (or power-cuts) a TPUStore and revive_osd REMOUNTS the
same directory — acked data survives real kill/remount cycles, a
revived OSD with an intact store recovers via the pg log (not full
backfill), scripted bit-rot is detected by the per-blob csum and
repaired from peers by scrub, and the fsid contract catches a fresh
store smuggled under a revived OSD id.

Sizing: CEPH_TPU_CRASH_SWEEP_TXNS shrinks the tier-1 sweep; the
full-duration thrash leg is marked slow.
"""

import asyncio
import os

import numpy as np
import pytest

from ceph_tpu.os import ObjectId, Transaction
from ceph_tpu.os.faultstore import (
    BrokenBlockStore,
    BrokenCommitStore,
    CrashSweep,
    FaultStore,
    build_image,
    durable_kv_prefix,
    snapshot_store,
    write_image,
)
from ceph_tpu.os.tpustore import TPUStore

from cluster_helpers import Cluster, tpustore_factory

SWEEP_TXNS = int(os.environ.get("CEPH_TPU_CRASH_SWEEP_TXNS", "24"))


# -- the sweep (tentpole acceptance) ---------------------------------------


def test_crash_sweep_mixed_workload_zero_violations(tmp_path):
    """The acceptance sweep: >= 200 distinct crash points (prefix,
    drop-subset, torn-write schedules) over the mixed workload, zero
    invariant violations, with double-crash-during-replay legs
    exercised."""
    rep = CrashSweep(str(tmp_path)).run(txns=SWEEP_TXNS, seed=0)
    assert not rep["violations"], rep["violations"][:5]
    floor = 200 if SWEEP_TXNS >= 24 else 8 * SWEEP_TXNS
    assert rep["points"] >= floor, rep
    assert rep["double_crash_points"] >= 1, \
        "no crash-during-replay schedule ran"
    assert rep["txns"] == SWEEP_TXNS


def test_crash_sweep_is_seed_sensitive_but_stable(tmp_path):
    """Two sweeps over the same seed explore the same trace (the
    synthesis is deterministic — a violation is reproducible)."""
    r1 = CrashSweep(str(tmp_path / "a")).run(txns=6, seed=3,
                                             double_crash=False)
    r2 = CrashSweep(str(tmp_path / "b")).run(txns=6, seed=3,
                                             double_crash=False)
    assert (r1["points"], r1["events"]) == (r2["points"], r2["events"])
    assert not r1["violations"] and not r2["violations"]


def test_sweep_catches_store_without_block_fsync(tmp_path):
    """Harness self-test: remove the pre-commit block fsync and the
    sweep must report violations (lost payloads under committed
    onodes surface as csum failures or model divergence)."""
    rep = CrashSweep(str(tmp_path), store_cls=BrokenBlockStore).run(
        txns=8, seed=1, double_crash=False)
    assert rep["violations"], "fsync-less store passed the sweep"


def test_sweep_catches_store_without_sync_commit(tmp_path):
    """Self-test twin: demote the commit point to a non-sync KV batch
    and acked transactions become losable — the sweep must flag the
    ack/durability inversion."""
    rep = CrashSweep(str(tmp_path), store_cls=BrokenCommitStore).run(
        txns=8, seed=1, double_crash=False)
    assert any("not durable" in v for v in rep["violations"]), \
        rep["violations"][:3]


def test_powercut_preserves_acked_writes(tmp_path):
    """Unit shape of the tentpole claim: acked direct AND deferred
    writes survive crash_powercut + remount; the deferred WAL replays
    on mount."""
    d = str(tmp_path / "s")
    s = FaultStore(d)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    s.queue_transaction(t)
    acked = []
    t = Transaction()
    t.write("c", ObjectId("a"), 0, 5000, b"x" * 5000)
    t.register_on_commit(lambda: acked.append("direct"))
    s.queue_transaction(t)
    t = Transaction()
    t.write("c", ObjectId("a"), 100, 50, b"Y" * 50)  # deferred path
    t.register_on_commit(lambda: acked.append("deferred"))
    s.queue_transaction(t)
    assert acked == ["direct", "deferred"]
    assert s.perf["deferred_writes"] >= 1
    fsid = s.fsid
    s.crash_powercut()
    s2 = TPUStore(d)
    s2.mount()
    assert s2.fsid == fsid
    got = s2.read("c", ObjectId("a"))
    assert got[100:150] == b"Y" * 50 and got[:100] == b"x" * 100
    assert s2.perf["journal_replays"] == 1
    assert s2.perf["journal_replayed_bytes"] >= 50
    s2.umount()


def test_double_crash_inside_replay_is_idempotent(tmp_path):
    """tpustore.py claims replay idempotence; prove it: power-cut with
    pending deferred entries, then cut the REPLAY's own writes at
    every point and remount a third time — the deferred data must
    still be exactly visible."""
    d = str(tmp_path / "s")
    s = FaultStore(d)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    s.queue_transaction(t)
    t = Transaction()
    t.write("c", ObjectId("a"), 0, 8000, b"x" * 8000)
    s.queue_transaction(t)
    for i in range(3):  # several live journal entries
        t = Transaction()
        t.write("c", ObjectId("a"), 1000 * i, 64, bytes([65 + i]) * 64)
        s.queue_transaction(t)
    assert s.perf["deferred_writes"] == 3
    s.crash_powercut()

    # first remount records its replay trace
    probe = FaultStore(d)
    probe.mount()
    replay = list(probe.crashlog.events)
    base_block, base_kv = probe.base_block, probe.base_kv
    probe.crash()
    assert any(ev[0] == "write" for ev in replay), "replay did nothing"

    img = str(tmp_path / "img")
    checked = 0
    for inner in range(1, len(replay) + 1):
        block, ops = build_image(replay, inner, drop_pending=True,
                                 kv_keep="min", base_block=base_block)
        write_image(img, block, ops, base_kv=base_kv)
        s3 = TPUStore(img)
        s3.mount()  # second replay
        got = s3.read("c", ObjectId("a"))
        for i in range(3):
            assert got[1000 * i:1000 * i + 64] == bytes([65 + i]) * 64
        s3.umount()
        checked += 1
    assert checked == len(replay)


def test_bitrot_detected_not_silently_served(tmp_path):
    """Scripted bit-rot flips a stored byte; the per-blob csum must
    fail the read (EIO shape), never return corrupt bytes."""
    s = FaultStore(str(tmp_path / "s"))
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    t.write("c", ObjectId("b"), 0, 4000, b"z" * 4000)
    s.queue_transaction(t)
    s.inject_bitrot("c", ObjectId("b"), byte=123)
    with pytest.raises(IOError):
        s.read("c", ObjectId("b"))
    assert s.perf["csum_read_failures"] == 1
    s.umount()


def test_snapshot_store_matches_itself_across_remount(tmp_path):
    """The model snapshot is remount-stable (the sweep's equality
    check is meaningful)."""
    d = str(tmp_path / "s")
    s = TPUStore(d)
    s.mkfs()
    s.mount()
    t = Transaction()
    t.create_collection("c")
    t.write("c", ObjectId("o"), 0, 3000, b"m" * 3000)
    t.setattr("c", ObjectId("o"), "a", b"v")
    t.omap_setkeys("c", ObjectId("o"), {"k": b"w"})
    t.omap_setheader("c", ObjectId("o"), b"h")
    s.queue_transaction(t)
    snap = snapshot_store(s)
    s.umount()
    s2 = TPUStore(d)
    s2.mount()
    assert snapshot_store(s2) == snap
    s2.umount()


def test_durable_kv_prefix_semantics():
    """min cuts at the last sync batch; max keeps the whole prefix."""
    events = [
        ("kv", [("set", "S", b"a", b"1")], True),
        ("kv", [("set", "S", b"b", b"2")], False),
        ("kv", [("set", "S", b"c", b"3")], True),
        ("kv", [("set", "S", b"d", b"4")], False),
    ]
    assert len(durable_kv_prefix(events, 4, "min")) == 3
    assert len(durable_kv_prefix(events, 4, "max")) == 4
    assert len(durable_kv_prefix(events, 2, "min")) == 1


# -- persistent clusters ---------------------------------------------------


def _run(coro, timeout):
    asyncio.run(asyncio.wait_for(coro, timeout))


def test_persistent_cluster_kill_remount_acked_data(tmp_path):
    """The thrash leg (smoke size): TPUStore-backed OSDs, real
    kill -> power-cut -> remount cycles with fault injection armed
    (CEPH_TPU_CRASH_INJECT default-on + FaultStore), RadosModel acked
    -data discipline — no acked write lost, bit-exact readback — and
    store_status shows remounts replaying the WAL."""
    import random

    async def main():
        rng = random.Random(17)
        cluster = Cluster(
            num_osds=4, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "crash", size=2, pg_num=8)
            io = cluster.client.open_ioctx("crash")
            nrng = np.random.default_rng(17)
            model: dict = {}

            async def write_some(n):
                for _ in range(n):
                    oid = f"obj-{rng.randrange(10)}"
                    data = nrng.integers(
                        0, 256, rng.randrange(500, 20_000),
                        dtype=np.uint8).tobytes()
                    await io.write_full(oid, data)
                    model[oid] = data  # acked: must survive anything

            await write_some(6)
            for cycle in range(4):
                osd = rng.choice(sorted(cluster.osds))
                await cluster.kill_osd(osd)
                await cluster.wait_for_osd_down(osd)
                await write_some(4)
                await cluster.revive_osd(osd)
                await cluster.wait_for_osd_up(osd)
                await cluster.wait_for_clean(timeout=90)
            for oid, want in model.items():
                assert await io.read(oid) == want, \
                    f"{oid}: acked write lost across kill/remount"
            # every store is a remount of its original disk
            for osd_id, store in cluster.stores.items():
                assert store.fsid == cluster.fsids[osd_id]
            rc, st = await cluster.client.osd_command(
                sorted(cluster.osds)[0], {"prefix": "store_status"})
            assert rc == 0
            assert st["type"] == "FaultStore" and st["mounted"]
            assert st["fsid"]
            assert "journal_replays" in st["perf"]
        finally:
            await cluster.stop()

    _run(main(), 420)


def test_persistent_revive_recovers_via_pg_log(tmp_path):
    """A revived OSD whose store is intact recovers the LOG DIFF
    (objects written while it was down), not the whole PG — the
    log-based-vs-backfill acceptance.  The log is trimmed aggressively
    so a fresh store WOULD have to backfill everything."""

    async def main():
        cluster = Cluster(
            num_osds=4, osds_per_host=1,
            osd_config={"osd_min_pg_log_entries": 8},
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "logs", size=2, pg_num=8)
            io = cluster.client.open_ioctx("logs")
            nrng = np.random.default_rng(5)
            total = 24
            for i in range(total):
                await io.write_full(
                    f"base-{i}",
                    nrng.integers(0, 256, 2000,
                                  dtype=np.uint8).tobytes())
            victim = 1
            await cluster.kill_osd(victim)
            await cluster.wait_for_osd_down(victim)
            while_down = 6
            for i in range(while_down):
                await io.write_full(
                    f"new-{i}",
                    nrng.integers(0, 256, 2000,
                                  dtype=np.uint8).tobytes())
            await cluster.revive_osd(victim)
            await cluster.wait_for_osd_up(victim)
            await cluster.wait_for_clean(timeout=120)
            rc, perf = await cluster.client.osd_command(
                victim, {"prefix": "perf dump"})
            assert rc == 0
            installs = perf["recovery_installs"]
            # log-driven: only what landed while down (about half the
            # new objects map to the victim), never the ~half of ALL
            # 30 objects a backfill would push
            assert 1 <= installs <= while_down + 2, installs
            assert installs < total // 2
        finally:
            await cluster.stop()

    _run(main(), 300)


def test_bitrot_repaired_from_peers_by_scrub(tmp_path):
    """End-to-end bit-rot repair: corrupt a TPUStore blob under a
    LIVE cluster; the per-blob csum turns the shard read into EIO,
    scrub detects the inconsistency and repairs it from peers through
    _scrub_repair, after which the shard reads clean again."""

    async def main():
        cluster = Cluster(
            num_osds=3, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_ec_pool(
                "rot", {"plugin": "ec_jax",
                        "technique": "reed_sol_van",
                        "k": "2", "m": "1",
                        "crush-failure-domain": "osd"}, pg_num=4)
            io = cluster.client.open_ioctx("rot")
            data = np.random.default_rng(9).integers(
                0, 256, 16_384, dtype=np.uint8).tobytes()
            await io.write_full("victim", data)
            pg = io.object_pg("victim")
            acting, primary = \
                cluster.mon.osdmap.pg_to_acting_osds(pg)
            # corrupt a NON-primary shard's stored blob
            idx, osd = next((i, o) for i, o in enumerate(acting)
                            if o != primary)
            cid = f"{pg.pool}.{pg.ps:x}s{idx}_head"
            store = cluster.stores[osd]
            store.inject_bitrot(cid, ObjectId("victim"), byte=77)
            with pytest.raises(IOError):
                store.read(cid, ObjectId("victim"))
            assert store.perf["csum_read_failures"] >= 1
            # the client still reads clean (decode works around EIO)
            assert await io.read("victim") == data
            # scrub on the primary detects + repairs via recovery
            prim = cluster.osds[primary]
            state = prim.pgs[pg]
            pool = prim.osdmap.pools[pg.pool]
            run = await prim.scrub_pg(state, pool)
            assert run["errors"] >= 1, run
            assert run["repaired"] >= 1, run
            # the corrupt shard was reinstalled: reads clean now
            assert store.read(cid, ObjectId("victim")) is not None
            assert await io.read("victim") == data
        finally:
            await cluster.stop()

    _run(main(), 300)


def test_revive_with_fresh_store_trips_fsid_assert(tmp_path):
    """The explicit revive contract: a wiped + re-mkfs'd directory
    under a revived OSD id fails the fsid assertion instead of
    silently booting loss-and-backfill."""
    import shutil

    async def main():
        cluster = Cluster(
            num_osds=3, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path),
            persistent=True)
        await cluster.start()
        try:
            await cluster.kill_osd(2)
            await cluster.wait_for_osd_down(2)
            # wipe the disk and format a FRESH store at the same path
            shutil.rmtree(os.path.join(str(tmp_path), "osd-2"))
            fresh = tpustore_factory(tmp_path)(2)
            fresh.mkfs()
            with pytest.raises(AssertionError, match="fsid"):
                await cluster.revive_osd(2)
        finally:
            await cluster.stop()

    _run(main(), 180)


def test_store_counters_scrapeable_via_prometheus(tmp_path):
    """The perf-dump `store` section flattens to ceph_osd_store_*
    gauges (journal replays, csum failures, deferred depth) — the
    operator can alert on durability health."""

    async def main():
        from ceph_tpu.mgr import MgrDaemon

        cluster = Cluster(
            num_osds=3, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "pm", size=2, pg_num=4)
            io = cluster.client.open_ioctx("pm")
            await io.write_full("x", b"p" * 9000)
            mgr = MgrDaemon(cluster.mon.addr, config={})
            await mgr.start()
            try:
                prom = mgr.modules["prometheus"]
                host, port = prom.addr.split(":")
                reader, writer = await asyncio.open_connection(
                    host, int(port))
                writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
                await writer.drain()
                raw = await asyncio.wait_for(reader.read(), 10.0)
                writer.close()
                body = raw.decode().split("\r\n\r\n", 1)[1]
                assert "ceph_osd_store_kv_commits" in body
                assert "ceph_osd_store_journal_replays" in body
                assert "ceph_osd_store_csum_read_failures" in body
                assert "ceph_osd_store_deferred_queue_depth" in body
            finally:
                await mgr.stop()
        finally:
            await cluster.stop()

    _run(main(), 240)


def test_crash_inject_kill_switch(tmp_path, monkeypatch):
    """CEPH_TPU_CRASH_INJECT=0: kill_osd degrades to the plain
    process-crash close (no power-cut synthesis) — everything the
    process wrote survives, including un-synced journal tails."""
    monkeypatch.setenv("CEPH_TPU_CRASH_INJECT", "0")

    async def main():
        cluster = Cluster(
            num_osds=3, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "ks", size=2, pg_num=4)
            io = cluster.client.open_ioctx("ks")
            await io.write_full("o", b"k" * 5000)
            await cluster.kill_osd(1)
            await cluster.wait_for_osd_down(1)
            await cluster.revive_osd(1)
            await cluster.wait_for_osd_up(1)
            await cluster.wait_for_clean(timeout=90)
            assert await io.read("o") == b"k" * 5000
        finally:
            await cluster.stop()

    _run(main(), 240)


# -- slow tier -------------------------------------------------------------


@pytest.mark.slow
def test_crash_sweep_full(tmp_path):
    """The exhaustive sweep: a bigger workload, two seeds, every
    schedule + double-crash legs."""
    for seed in (0, 7):
        rep = CrashSweep(str(tmp_path / f"s{seed}")).run(
            txns=40, seed=seed)
        assert not rep["violations"], rep["violations"][:5]
        assert rep["points"] >= 300


@pytest.mark.slow
def test_thrash_tpustore_persistent(tmp_path):
    """Full-duration thrash over TPUStore-backed OSDs: concurrent
    writes racing kill -> power-cut -> remount cycles, the acked-data
    discipline checked object by object."""
    import random

    async def main():
        rng = random.Random(4321)
        cluster = Cluster(
            num_osds=5, osds_per_host=1,
            store_factory=tpustore_factory(tmp_path, fault=True),
            persistent=True)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "tp", size=3, pg_num=8)
            io = cluster.client.open_ioctx("tp")
            nrng = np.random.default_rng(4321)
            # RadosModel discipline: an ACKED write must stick; an
            # UNACKED attempt may still have committed, so the legal
            # readback states are {last acked} U {attempts since}
            model: dict = {}
            maybe: dict = {}
            stop = False

            async def workload():
                seq = 0
                while not stop:
                    seq += 1
                    oid = f"obj-{rng.randrange(12)}"
                    data = nrng.integers(
                        0, 256, rng.randrange(1000, 40_000),
                        dtype=np.uint8).tobytes()
                    maybe.setdefault(oid, []).append(data)
                    try:
                        await io.write_full(oid, data)
                        model[oid] = data
                        maybe[oid] = []
                    except Exception:
                        pass  # indeterminate: stays in maybe
                    await asyncio.sleep(0)

            task = asyncio.get_running_loop().create_task(workload())
            try:
                for _ in range(10):
                    osd = rng.choice(sorted(cluster.osds))
                    await cluster.kill_osd(osd)
                    await cluster.wait_for_osd_down(osd)
                    await asyncio.sleep(1.0)
                    await cluster.revive_osd(osd)
                    await cluster.wait_for_osd_up(osd)
                    await cluster.wait_for_clean(timeout=120)
            finally:
                stop = True
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            await cluster.wait_for_clean(timeout=120)
            for oid, want in model.items():
                got = await io.read(oid)
                legal = [want] + maybe.get(oid, [])
                assert any(got == w for w in legal), \
                    f"{oid}: readback matches neither the acked" \
                    f" state nor any of {len(maybe.get(oid, []))}" \
                    " indeterminate attempts"
        finally:
            await cluster.stop()

    _run(main(), 900)

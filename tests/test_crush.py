"""CRUSH tests: map model, host mapper behavior, TPU-kernel parity.

The host mapper's ground truth is established against the reference's
compiled C in test_crush_oracle.py; here the vmapped JAX kernel must match
the host mapper placement-for-placement (transitively: diff=0 vs the
reference), plus distribution sanity checks in the CrushTester spirit
(/root/reference/src/crush/CrushTester.cc:477).
"""

import numpy as np
import pytest

from ceph_tpu.crush.map import (
    CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_TAKE, Rule, RuleStep,
    CrushMap, build_flat_cluster)
from ceph_tpu.crush.mapper import crush_do_rule


def ec_rule(cmap, name="ec", leaf_tries=5):
    """The OSDMonitor-style EC rule: SET_CHOOSELEAF_TRIES + chooseleaf indep."""
    return cmap.add_rule(Rule(name, [
        RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, leaf_tries),
        RuleStep(CRUSH_RULE_TAKE, cmap.name_to_item("default")),
        RuleStep(CRUSH_RULE_CHOOSELEAF_INDEP, 0, cmap.type_id("host")),
        RuleStep(CRUSH_RULE_EMIT),
    ], rule_type=3))


def test_firstn_basic_properties():
    cmap = build_flat_cluster(32, osds_per_host=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    for x in range(200):
        res = crush_do_rule(cmap, 0, x, 3)
        assert len(res) == 3
        assert len(set(res)) == 3  # distinct devices
        hosts = {r // 4 for r in res}
        assert len(hosts) == 3  # distinct failure domains


def test_indep_positional_stability():
    # knocking out a device must not shuffle surviving positions
    cmap = build_flat_cluster(40, osds_per_host=4)
    ec_rule(cmap)
    w = cmap.full_weight_vector()
    base = {x: crush_do_rule(cmap, 0, x, 6, w) for x in range(100)}
    dead = base[0][2]
    w2 = list(w)
    w2[dead] = 0
    moved = 0
    for x in range(100):
        after = crush_do_rule(cmap, 0, x, 6, w2)
        for pos, (a, b) in enumerate(zip(base[x], after)):
            if a == dead:
                assert b != dead
            elif a != b:
                moved += 1
    # positional stability: survivors rarely move (only cascading collisions)
    assert moved <= 2


def test_weight_drives_distribution():
    cmap = CrushMap()
    root = cmap.add_bucket(-1, cmap.type_id("root"), "default")
    for i in range(4):
        cmap.add_device(i)
        root.add_item(i, (i + 1) * 0x10000)  # weights 1,2,3,4
    cmap.add_simple_rule("flat", "default", "osd", mode="firstn")
    counts = np.zeros(4)
    for x in range(4000):
        counts[crush_do_rule(cmap, 0, x, 1)[0]] += 1
    frac = counts / counts.sum()
    want = np.array([1, 2, 3, 4]) / 10
    assert np.all(np.abs(frac - want) < 0.03), frac


def test_out_device_never_chosen():
    cmap = build_flat_cluster(16, osds_per_host=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    w = cmap.full_weight_vector()
    w[5] = 0
    for x in range(500):
        assert 5 not in crush_do_rule(cmap, 0, x, 3, w)


# -- TPU kernel parity ----------------------------------------------------


def _host_all(cmap, ruleno, xs, result_max, w=None):
    return [crush_do_rule(cmap, ruleno, x, result_max, w) for x in xs]


def _pad(lst, n):
    return lst + [CRUSH_ITEM_NONE] * (n - len(lst))


@pytest.mark.parametrize("shape", ["flat", "racks"])
def test_kernel_matches_host_firstn(shape):
    from ceph_tpu.crush.kernel import compile_rule

    if shape == "flat":
        cmap = build_flat_cluster(64, osds_per_host=4)
    else:
        cmap = build_flat_cluster(96, osds_per_host=4, hosts_per_rack=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    xs = np.arange(512)
    run = compile_rule(cmap, 0, 3)
    got = run(xs)
    want = _host_all(cmap, 0, xs, 3)
    for i, x in enumerate(xs):
        assert list(got[i]) == _pad(want[i], 3), x


def test_kernel_matches_host_indep_ec():
    from ceph_tpu.crush.kernel import compile_rule

    cmap = build_flat_cluster(96, osds_per_host=4, hosts_per_rack=4)
    ec_rule(cmap)
    xs = np.arange(512)
    run = compile_rule(cmap, 0, 11)
    got = run(xs)
    want = _host_all(cmap, 0, xs, 11)
    for i, x in enumerate(xs):
        assert list(got[i]) == _pad(want[i], 11), x


def test_kernel_matches_host_reweighted():
    from ceph_tpu.crush.kernel import compile_rule

    rng = np.random.default_rng(5)
    cmap = build_flat_cluster(64, osds_per_host=4)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    ec_rule(cmap)
    w = [int(v) for v in rng.integers(0, 0x10001, 64)]
    xs = np.arange(512)
    for ruleno, rmax in ((0, 3), (1, 8)):
        run = compile_rule(cmap, ruleno, rmax, weight=w)
        got = run(xs)
        want = _host_all(cmap, ruleno, xs, rmax, w)
        for i, x in enumerate(xs):
            assert list(got[i]) == _pad(want[i], rmax), (ruleno, x)


def test_kernel_matches_host_choose_osd():
    from ceph_tpu.crush.kernel import compile_rule

    cmap = build_flat_cluster(40, osds_per_host=40)
    cmap.add_simple_rule("flat", "default", "osd", mode="firstn")
    xs = np.arange(1024)
    run = compile_rule(cmap, 0, 3)
    got = run(xs)
    want = _host_all(cmap, 0, xs, 3)
    for i, x in enumerate(xs):
        assert list(got[i]) == _pad(want[i], 3), x


def test_kernel_10k_bulk():
    from ceph_tpu.crush.kernel import compile_rule

    cmap = build_flat_cluster(10000, osds_per_host=20, hosts_per_rack=10)
    cmap.add_simple_rule("data", "default", "host", mode="firstn")
    xs = np.arange(100_000)
    run = compile_rule(cmap, 0, 3)
    got = run(xs)
    assert got.shape == (100_000, 3)
    # spot-check against host
    for x in range(0, 100_000, 9973):
        assert list(got[x]) == _pad(crush_do_rule(cmap, 0, x, 3), 3)
    # all placements valid & distinct
    assert (got >= 0).all() and (got < 10000).all()
    assert (got[:, 0] != got[:, 1]).all()

"""Self-managed snapshot tier: clone-on-write, read-at-snap, whiteout,
trim — the write/snap/overwrite/read-at-snap/trim round-trip of the
reference's snapshot model (PrimaryLogPG make_writeable, SnapSet,
SnapMapper trim; /root/reference/src/osd/SnapMapper.h:102)."""

import asyncio

import numpy as np
import pytest

from cluster_helpers import Cluster

EC22 = {"plugin": "ec_jax", "technique": "reed_sol_van",
        "k": "2", "m": "2", "crush-failure-domain": "osd",
        "tpu": "false"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _snap_round_trip(cluster, make_pool):
    io = await make_pool(cluster)
    v1 = bytes(np.random.default_rng(1).integers(0, 256, 60_000,
                                                 dtype=np.uint8))
    v2 = bytes(np.random.default_rng(2).integers(0, 256, 70_000,
                                                 dtype=np.uint8))
    v3 = bytes(np.random.default_rng(3).integers(0, 256, 40_000,
                                                 dtype=np.uint8))
    await io.write_full("obj", v1)
    s1 = await io.create_selfmanaged_snap()
    await io.write_full("obj", v2)          # clones v1 under s1
    s2 = await io.create_selfmanaged_snap()
    await io.write("obj", v3, 10_000)       # partial write clones v2
    head = bytearray(v2)
    head[10_000:10_000 + len(v3)] = v3

    assert await io.read("obj") == bytes(head)
    io.snap_set_read(s1)
    assert await io.read("obj") == v1
    io.snap_set_read(s2)
    assert await io.read("obj") == v2
    io.snap_set_read(0)
    assert await io.read("obj") == bytes(head)
    # snap reads of never-written objects miss
    io.snap_set_read(s1)
    with pytest.raises(Exception):
        await io.read("nope")
    io.snap_set_read(0)
    return io, v1, v2, bytes(head), s1, s2


def test_replicated_snap_round_trip_and_trim():
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            async def mk(c):
                await c.client.create_replicated_pool(
                    "p", size=3, pg_num=8)
                return c.client.open_ioctx("p")

            io, v1, v2, head, s1, s2 = await _snap_round_trip(
                cluster, mk)

            # trim s1: its clone dies once every primary observes the
            # removal; s2's data must survive
            await io.remove_selfmanaged_snap(s1)
            await asyncio.sleep(1.0)
            io.snap_set_read(s2)
            assert await io.read("obj") == v2
            io.snap_set_read(0)
            assert await io.read("obj") == head
            # the s1 clone object is gone from every store
            for osd in cluster.osds.values():
                for cid in osd.store.list_collections():
                    for o in osd.store.list_objects(cid):
                        assert f"obj\x16{s1}" != str(o), \
                            f"untrimmed clone on osd {cid}"
        finally:
            await cluster.stop()

    run(main())


def test_ec_snap_round_trip():
    async def main():
        cluster = Cluster(num_osds=5)
        await cluster.start()
        try:
            async def mk(c):
                await c.client.create_ec_pool(
                    "ec", profile=EC22, pg_num=8)
                return c.client.open_ioctx("ec")

            await _snap_round_trip(cluster, mk)
        finally:
            await cluster.stop()

    run(main())


def test_remove_with_snaps_whiteout_then_trim():
    """Deleting a snapshotted object hides it from reads/listing but
    keeps snap data readable until the snaps are removed; trimming the
    last snap finishes the delete."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            await io.write_full("obj", b"alive" * 1000)
            snap = await io.create_selfmanaged_snap()
            await io.remove("obj")
            with pytest.raises(Exception):
                await io.read("obj")
            assert await io.list_objects() == []
            io.snap_set_read(snap)
            assert await io.read("obj") == b"alive" * 1000
            io.snap_set_read(0)
            # trim the snap: everything about the object disappears
            await io.remove_selfmanaged_snap(snap)
            await asyncio.sleep(1.0)
            for osd in cluster.osds.values():
                for cid in osd.store.list_collections():
                    for o in osd.store.list_objects(cid):
                        assert "obj" not in str(o) or \
                            "_pgmeta_" in str(o), f"leftover {o}"
        finally:
            await cluster.stop()

    run(main())


def test_snap_before_creation_is_enoent():
    """A snap taken before an object existed must read ENOENT at that
    snap, even after later writes create clones (review r3)."""
    async def main():
        cluster = Cluster(num_osds=4)
        await cluster.start()
        try:
            await cluster.client.create_replicated_pool(
                "p", size=3, pg_num=8)
            io = cluster.client.open_ioctx("p")
            s1 = await io.create_selfmanaged_snap()   # before creation
            await io.write_full("late", b"born" * 500)
            s2 = await io.create_selfmanaged_snap()
            await io.write_full("late", b"grew" * 600)
            io.snap_set_read(s1)
            with pytest.raises(Exception):
                await io.read("late")
            io.snap_set_read(s2)
            assert await io.read("late") == b"born" * 500
            # snapless client's remove must keep clones reachable
            io2 = cluster.client.open_ioctx("p")
            await io2.remove("late")
            io.snap_set_read(s2)
            assert await io.read("late") == b"born" * 500
        finally:
            await cluster.stop()

    run(main())

"""mgr rbd_support module (pybind/mgr/rbd_support role): snapshot
schedules with retention and trash purge schedules, driven by the
module's serve loop off cluster-stored schedule data."""

import asyncio

from cluster_helpers import Cluster

from ceph_tpu.mgr import MgrDaemon
from ceph_tpu.mgr.rbd_support import RbdSupportModule
from ceph_tpu.rbd import RBD


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 150))


def test_snapshot_schedule_with_retention_and_trash_purge():
    async def main():
        cluster = Cluster(num_osds=3)
        await cluster.start()
        mgr = None
        try:
            await cluster.client.create_replicated_pool(
                "rbd", size=2, pg_num=4)
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io, "vm", 1 << 20, order=18)
            img = await rbd.open(io, "vm")
            await img.write(0, b"scheduled data")
            await img.close()
            # a manual snapshot the schedule must never prune
            img = await rbd.open(io, "vm")
            await img.snap_create("manual")
            await img.close()
            # expired trash entry for the purge schedule
            await rbd.create(io, "old", 1 << 20, order=18)
            await rbd.trash_mv(io, "old")

            await RbdSupportModule.schedule_snapshots(
                io, "vm", interval=0.5, keep=2)
            await RbdSupportModule.schedule_trash_purge(
                io, interval=0.5)
            scheds = await RbdSupportModule.schedule_ls(io)
            assert len(scheds) == 2

            mgr = MgrDaemon(cluster.mon.addr,
                            modules=["rbd_support"],
                            tick_interval=0.3)
            await mgr.start()
            # several intervals pass: snapshots accumulate but stay
            # capped at keep=2; the trash drains
            for _ in range(60):
                await asyncio.sleep(0.4)
                img = await rbd.open(io, "vm")
                mine = [s for s in img.meta["snaps"]
                        if s.startswith("scheduled-")]
                trash = await rbd.trash_ls(io)
                if len(mine) >= 2 and not trash:
                    break
            img = await rbd.open(io, "vm")
            mine = [s for s in img.meta["snaps"]
                    if s.startswith("scheduled-")]
            assert 1 <= len(mine) <= 2, img.meta["snaps"]
            assert "manual" in img.meta["snaps"]  # never pruned
            assert await rbd.trash_ls(io) == []   # purge ran
            # schedule removal stops the machinery
            await RbdSupportModule.schedule_rm(io, "snap\x1fvm")
            assert len(await RbdSupportModule.schedule_ls(io)) == 1
        finally:
            if mgr is not None:
                await mgr.stop()
            await cluster.stop()
    run(main())

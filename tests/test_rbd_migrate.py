"""RBD deep-copy and migration (librbd deep_copy/ + api/Migration.cc
roles).

1. deep_copy replicates data AND snapshot history (per-snap content,
   protection flags), within and across clusters;
2. the delta passes move unchanged data once;
3. migration: prepare links dst to src (reads fall through
   immediately), execute copies, commit deletes the source; the
   source is write-fenced after prepare;
4. abort backs out cleanly.
"""

import asyncio

import pytest

from cluster_helpers import Cluster

from ceph_tpu.rados.client import RadosError
from ceph_tpu.rbd import RBD
from ceph_tpu.rbd.migrate import (
    deep_copy,
    migration_abort,
    migration_commit,
    migration_execute,
    migration_prepare,
)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def _cluster(pools=("rbd",)):
    cluster = Cluster(num_osds=3)
    await cluster.start()
    for p in pools:
        await cluster.client.create_replicated_pool(p, size=2,
                                                    pg_num=4)
    return cluster


def test_deep_copy_with_snapshot_history():
    async def main():
        cluster = await _cluster()
        try:
            io = cluster.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io, "src", 4 << 20, order=20)
            img = await rbd.open(io, "src")
            await img.write(0, b"A" * 8192)
            await img.snap_create("s1")
            await img.snap_protect("s1")
            await img.write(0, b"B" * 4096)          # changes s2
            await img.write(1 << 20, b"C" * 4096)    # new data
            await img.snap_create("s2")
            await img.write(0, b"H" * 1024)          # head only
            await img.close()

            await deep_copy(io, "src", io, "dst")
            dst = await rbd.open(io, "dst")
            # head
            assert await dst.read(0, 1024) == b"H" * 1024
            assert await dst.read(1 << 20, 4096) == b"C" * 4096
            # snapshot views
            assert sorted(s["name"] for s in await dst.snap_list()) \
                == ["s1", "s2"]
            assert await dst.snap_is_protected("s1")
            dst.snap_set("s1")
            assert await dst.read(0, 8192) == b"A" * 8192
            assert await dst.read(1 << 20, 4096) == bytes(4096)
            dst.snap_set("s2")
            assert await dst.read(0, 4096) == b"B" * 4096
            assert await dst.read(4096, 4096) == b"A" * 4096
            assert await dst.read(1 << 20, 4096) == b"C" * 4096
        finally:
            await cluster.stop()
    run(main())


def test_deep_copy_across_clusters():
    async def main():
        ca, cb = await _cluster(), await _cluster()
        try:
            io_a = ca.client.open_ioctx("rbd")
            io_b = cb.client.open_ioctx("rbd")
            rbd = RBD()
            await rbd.create(io_a, "img", 2 << 20, order=20)
            img = await rbd.open(io_a, "img")
            await img.write(0, b"xyz" * 1000)
            await img.snap_create("snap")
            await img.close()
            await deep_copy(io_a, "img", io_b, "img")
            got = await rbd.open(io_b, "img")
            assert await got.read(0, 3000) == b"xyz" * 1000
            assert [s["name"] for s in await got.snap_list()] == \
                ["snap"]
        finally:
            await ca.stop()
            await cb.stop()
    run(main())


def test_migration_lifecycle():
    async def main():
        cluster = await _cluster(pools=("rbd", "fast"))
        try:
            io = cluster.client.open_ioctx("rbd")
            fast = cluster.client.open_ioctx("fast")
            rbd = RBD()
            await rbd.create(io, "vm", 2 << 20, order=20)
            img = await rbd.open(io, "vm")
            await img.write(0, b"boot" * 256)
            await img.write(1 << 20, b"data" * 256)
            await img.close()

            await migration_prepare(io, "vm", fast, "vm")
            # reads fall through BEFORE any copying
            dst = await rbd.open(fast, "vm")
            assert await dst.read(0, 1024) == b"boot" * 256
            # the source is write-fenced now
            src = await rbd.open(io, "vm")
            with pytest.raises(RadosError):
                await src.write(0, b"nope")
            # destination takes live writes during migration
            await dst.write(4096, b"LIVE" * 256)
            await migration_execute(fast, "vm")
            # flattened: content self-contained
            assert await dst.read(1 << 20, 1024) == b"data" * 256
            assert await dst.read(4096, 1024) == b"LIVE" * 256
            await migration_commit(fast, "vm")
            assert "vm" not in await rbd.list(io)      # source gone
            fresh = await rbd.open(fast, "vm")
            assert fresh.meta.get("migration_source") is None
            assert await fresh.read(0, 1024) == b"boot" * 256
            await dst.close()
        finally:
            await cluster.stop()
    run(main())


def test_migration_abort():
    async def main():
        cluster = await _cluster(pools=("rbd", "fast"))
        try:
            io = cluster.client.open_ioctx("rbd")
            fast = cluster.client.open_ioctx("fast")
            rbd = RBD()
            await rbd.create(io, "img", 1 << 20, order=20)
            img = await rbd.open(io, "img")
            await img.write(0, b"keepme!!")
            await img.close()
            await migration_prepare(io, "img", fast, "img")
            await migration_abort(fast, "img")
            assert "img" not in await rbd.list(fast)
            # source unfenced and intact
            src = await rbd.open(io, "img")
            assert src.meta.get("migration") is None
            await src.write(8, b"writable")
            assert await src.read(0, 16) == b"keepme!!writable"
            await src.close()
        finally:
            await cluster.stop()
    run(main())


def test_migration_refuses_snapshotted_source():
    async def main():
        cluster = await _cluster(pools=("rbd", "fast"))
        try:
            io = cluster.client.open_ioctx("rbd")
            fast = cluster.client.open_ioctx("fast")
            rbd = RBD()
            await rbd.create(io, "s", 1 << 20, order=20)
            img = await rbd.open(io, "s")
            await img.snap_create("x")
            await img.close()
            with pytest.raises(RadosError):
                await migration_prepare(io, "s", fast, "s")
        finally:
            await cluster.stop()
    run(main())
